"""HTTP list/watch transport: a real apiserver REST client.

Reference counterpart: client-go's reflector + REST client as wired by
pkg/client/ and cmd/kube-batch/app/server.go · buildConfig — the
reference watches the apiserver over HTTP(S) chunked list/watch streams
and writes back REST verbs.  This module is that transport for the
rebuild:

* `Reflector` (one per resource): LIST (recording the collection
  resourceVersion) → WATCH from that RV → on stream drop, re-WATCH
  from the last-seen RV → on 410 Gone (or any ERROR event), full
  re-LIST — client-go's reflector loop.
* `HttpWatchMux`: runs one reflector thread per resource and
  multiplexes their events into a single line-iterable consumed by
  `K8sWatchAdapter` unchanged (list items get their `kind` injected —
  apiserver lists strip item kinds).  After every resource's initial
  LIST lands, a SYNC marker is emitted (≙ WaitForCacheSync).
* `K8sHttpBackend`: the Binder/Evictor/StatusUpdater/EventSink seam
  issuing the apiserver-shaped writes of client/k8s_write.py as real
  HTTP requests (Binding POST, graceful DELETE, status PUT, Event
  POST).

Auth/TLS lowering: a bearer token (``--kube-token-file``) rides the
Authorization header; https URLs use the default ssl context (or an
unverified one with ``insecure=True`` — kubeconfig parsing and client
certs are deliberately out of scope without a live cluster to verify
against).  `HttpLeaseElector` runs leader election on a
coordination.k8s.io/v1 Lease with apiserver optimistic concurrency —
the actual resourcelock `leaderelection.RunOrDie` uses.
"""

from __future__ import annotations

import http.client
import json
import logging
import queue
import ssl
import threading
import urllib.parse
from typing import Iterator

from kube_batch_tpu.cache.cluster import Pod, PodGroup
from kube_batch_tpu.client.k8s_write import (
    binding_request,
    event_request,
    evict_request,
    pod_group_status_request,
)

log = logging.getLogger(__name__)

#: The resources the reference's 8 informers watch, as (kind, path)
#: pairs.  PodGroup/Queue live under the incubator CRD group.
DEFAULT_RESOURCES: tuple[tuple[str, str], ...] = (
    ("Pod", "/api/v1/pods"),
    ("Node", "/api/v1/nodes"),
    ("PodGroup",
     "/apis/scheduling.incubator.k8s.io/v1alpha1/podgroups"),
    ("Queue", "/apis/scheduling.incubator.k8s.io/v1alpha1/queues"),
    ("PriorityClass", "/apis/scheduling.k8s.io/v1/priorityclasses"),
    ("PodDisruptionBudget", "/apis/policy/v1/poddisruptionbudgets"),
    ("Namespace", "/api/v1/namespaces"),
)

#: Alternate CRD versions per kind, probed in turn when the primary
#: path 404s.  ≙ the reference registering BOTH AddPodGroupV1alpha1
#: and AddPodGroupV1alpha2 informer handlers (cache/event_handlers.go):
#: a cluster serves whichever version its CRDs install; decode is
#: version-agnostic (kind-routed, same field names; v1alpha2's extra
#: spec.minResources is noted loudly by the decoder, not lowered).
ALT_RESOURCE_PATHS: dict[str, tuple[str, ...]] = {
    "PodGroup": (
        "/apis/scheduling.incubator.k8s.io/v1alpha2/podgroups",),
    "Queue": ("/apis/scheduling.incubator.k8s.io/v1alpha2/queues",),
}


class HttpError(RuntimeError):
    def __init__(self, status: int, body: str) -> None:
        super().__init__(f"HTTP {status}: {body[:200]}")
        self.status = status


class _Client:
    """One-request-per-call HTTP client (stdlib http.client): simple,
    thread-safe by construction (a fresh connection per call), and
    honest about what is tested — no pooling to go subtly wrong."""

    def __init__(
        self,
        base_url: str,
        token: str | None = None,
        token_file: str | None = None,
        insecure: bool = False,
        timeout: float = 30.0,
    ) -> None:
        u = urllib.parse.urlsplit(base_url)
        if u.scheme not in ("http", "https"):
            raise ValueError(f"unsupported scheme {u.scheme!r}")
        self.scheme = u.scheme
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or (443 if u.scheme == "https" else 80)
        # Base-URL path prefix survives (kubectl proxy, Rancher-style
        # /k8s/clusters/<id> — resources hang off the prefix there).
        self.prefix = u.path.rstrip("/")
        self.token = token
        # A bound serviceaccount token ROTATES; re-read per request
        # (mtime-cached) like client-go, or every call 401s an hour in.
        self.token_file = token_file
        self._token_cache: tuple[float, str] | None = None
        self.timeout = timeout
        self.ssl_ctx = None
        if u.scheme == "https":
            self.ssl_ctx = (
                ssl._create_unverified_context() if insecure
                else ssl.create_default_context()
            )

    def connect(self, timeout: float | None = None) -> http.client.HTTPConnection:
        if self.scheme == "https":
            return http.client.HTTPSConnection(
                self.host, self.port, timeout=timeout or self.timeout,
                context=self.ssl_ctx,
            )
        return http.client.HTTPConnection(
            self.host, self.port, timeout=timeout or self.timeout,
        )

    def _bearer(self) -> str | None:
        if self.token_file:
            import os

            try:
                mtime = os.stat(self.token_file).st_mtime
                if (
                    self._token_cache is None
                    or self._token_cache[0] != mtime
                ):
                    with open(self.token_file, encoding="utf-8") as f:
                        self._token_cache = (mtime, f.read().strip())
                return self._token_cache[1]
            except OSError as exc:
                log.warning("token file unreadable: %s", exc)
                return self._token_cache[1] if self._token_cache else None
        return self.token

    def _headers(self, extra: dict | None = None) -> dict:
        h = {"Accept": "application/json"}
        tok = self._bearer()
        if tok:
            h["Authorization"] = f"Bearer {tok}"
        # Cross-scheduler trace stitching, HTTP dialect: the calling
        # thread's active flow rides every request as the standard
        # W3C traceparent header (doc/design/observability.md · wire
        # format) — absent entirely when tracing is off.
        from kube_batch_tpu import trace

        tp = trace.wire_traceparent()
        if tp is not None:
            h["traceparent"] = tp
        if extra:
            h.update(extra)
        return h

    def request_json(
        self, method: str, path: str, body: dict | None = None
    ) -> dict:
        conn = self.connect()
        try:
            payload = None
            headers = self._headers()
            if body is not None:
                payload = json.dumps(body)
                headers["Content-Type"] = "application/json"
            conn.request(
                method, self.prefix + path, body=payload, headers=headers
            )
            resp = conn.getresponse()
            data = resp.read().decode("utf-8", "replace")
            if resp.status >= 300:
                raise HttpError(resp.status, data)
            return json.loads(data) if data.strip() else {}
        finally:
            conn.close()


class Reflector:
    """client-go's reflector loop for ONE resource, emitting watch-event
    JSON lines (with `kind` injected) into a shared sink."""

    def __init__(
        self,
        client: _Client,
        kind: str,
        path: str,
        sink: "queue.Queue[str | None]",
        stop: threading.Event,
    ) -> None:
        self.client = client
        self.kind = kind
        self.path = path
        # Served-version rotation: on a CONFIRMED 404 the discovery
        # retry probes the next known version of this kind's CRD
        # before concluding "not installed".
        self.paths: tuple[str, ...] = (
            path, *ALT_RESOURCE_PATHS.get(kind, ()),
        )
        self._path_i = 0
        self._probes_this_sweep = 0
        self.sink = sink
        self.stop = stop
        self.last_rv: str = ""
        self.listed = threading.Event()  # first LIST complete
        self.relists = 0
        # The informer-store analog: last known object per key, so a
        # re-LIST can synthesize DELETED for objects that vanished
        # during the watch gap (client-go's Replace does exactly this;
        # without it a 410 re-list leaks the deleted objects' capacity
        # in the scheduler cache forever).
        self._known: dict[str, dict] = {}
        # 404 on LIST = the CRD isn't installed (fresh cluster, or the
        # operator installs kube-batch before its CRDs): sync EMPTY so
        # the daemon starts instead of blocking forever, and re-probe
        # discovery until the resource appears.  The DESTRUCTIVE flush
        # of a previously-listed view requires CONSECUTIVE 404s: in an
        # HA control plane one not-yet-synced apiserver replica can
        # answer a single 404 for a perfectly healthy CRD, and one
        # blip must not nuke live gang/queue state (client-go never
        # clears its store on a list error).
        self.crd_missing = False
        self._missing_streak = 0

    @staticmethod
    def _key(obj: dict) -> str:
        meta = obj.get("metadata") or {}
        return meta.get("uid") or meta.get("name") or ""

    def _emit(self, mtype: str, obj: dict) -> None:
        obj.setdefault("kind", self.kind)
        rv = (obj.get("metadata") or {}).get("resourceVersion")
        if rv is not None:
            self.last_rv = str(rv)
        key = self._key(obj)
        if key:
            if mtype == "DELETED":
                self._known.pop(key, None)
            else:
                self._known[key] = obj
        self.sink.put(json.dumps({"type": mtype, "object": obj}))

    #: How often a 404'd (CRD-less) resource re-probes discovery.
    CRD_RETRY_S = 30.0

    def _list(self) -> None:
        try:
            out = self.client.request_json("GET", self.path)
        except HttpError as exc:
            if exc.status == 404:
                self._missing_streak += 1
                self.crd_missing = True
                if self._known and self._missing_streak < 2:
                    # One blip: keep the live view; confirm shortly.
                    log.warning(
                        "%s: %s answered 404 once (lagging HA "
                        "replica?); keeping the live view, confirming "
                        "in 2s", self.kind, self.path,
                    )
                    self.listed.set()
                    return
                if self._missing_streak <= 2:
                    log.warning(
                        "%s: %s not served (404) — CRD not installed? "
                        "syncing empty; discovery retries every %.0fs",
                        self.kind, self.path, self.CRD_RETRY_S,
                    )
                # Confirmed (or nothing was listed): a runtime CRD
                # uninstall must flush everything previously listed or
                # its capacity leaks in the scheduler cache forever.
                for key in list(self._known):
                    self._emit("DELETED", self._known[key])
                self.listed.set()  # empty view; don't block the daemon
                return
            raise
        self.crd_missing = False
        self._missing_streak = 0
        self._probes_this_sweep = 0  # next 404 starts a fresh sweep
        fresh = {self._key(i): i for i in out.get("items", []) or []}
        # Objects that vanished during the gap: synthesize DELETED
        # before the upserts (≙ DeltaFIFO Replace).
        for key in [k for k in self._known if k not in fresh]:
            self._emit("DELETED", self._known[key])
        for item in fresh.values():
            self._emit("ADDED", item)
        rv = (out.get("metadata") or {}).get("resourceVersion")
        if rv is not None:
            self.last_rv = str(rv)
        self.listed.set()

    def _watch_once(self) -> bool:
        """One watch stream; returns True when a re-LIST is required
        (410/ERROR), False on a plain drop (re-watch from last RV).

        `timeoutSeconds` bounds every watch server-side (client-go's
        randomized minWatchTimeout): reads are deliberately blocking
        (a client read timeout corrupts mid-chunk state), so a
        half-open connection that lost its FIN would otherwise wedge
        this resource's reflector forever — the server ending the
        stream is what guarantees liveness."""
        params = {"watch": "1", "allowWatchBookmarks": "true",
                  "timeoutSeconds": str(300 + (id(self) % 240))}
        if self.last_rv:
            params["resourceVersion"] = self.last_rv
        q = urllib.parse.urlencode(params)
        conn = self.client.connect(timeout=10.0)
        try:
            conn.request(
                "GET", f"{self.client.prefix}{self.path}?{q}",
                headers=self.client._headers(),
            )
            resp = conn.getresponse()
            if resp.status == 410:
                return True
            if resp.status == 404:
                # The CRD vanished mid-watch: route into _list()'s
                # 404 handling (flush + empty-sync + discovery probe)
                # instead of spinning re-watch attempts forever.
                self.listed.clear()
                return False
            if resp.status >= 300:
                raise HttpError(resp.status, resp.read().decode(
                    "utf-8", "replace"))
            # Blocking reads from here on: a read timeout firing
            # mid-chunk corrupts http.client's buffered stream (the
            # same hazard cli.py's dial() documents), so the connect
            # timeout must not survive into the watch body.  Stop
            # responsiveness comes from the connection closing (the
            # mux is torn down with its process / server).
            if conn.sock is not None:
                conn.sock.settimeout(None)
            buf = b""
            while not self.stop.is_set():
                try:
                    chunk = resp.read1(65536)
                except OSError:
                    return False  # connection dropped: re-watch
                if not chunk:
                    return False  # stream closed by the server
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    if not line.strip():
                        continue
                    try:
                        msg = json.loads(line)
                    except json.JSONDecodeError:
                        log.warning("undecodable watch line: %.120s", line)
                        continue
                    mtype = msg.get("type")
                    if mtype == "BOOKMARK":
                        # Progress marker only: advance the resume
                        # point, emit nothing (≙ allowWatchBookmarks).
                        rv = ((msg.get("object") or {}).get("metadata")
                              or {}).get("resourceVersion")
                        if rv is not None:
                            self.last_rv = str(rv)
                        continue
                    if mtype == "ERROR":
                        code = (msg.get("object") or {}).get("code")
                        log.warning(
                            "%s watch ERROR (code %s); re-listing",
                            self.kind, code,
                        )
                        return True  # 410 Gone and friends
                    self._emit(mtype, msg.get("object") or {})
            return False
        finally:
            conn.close()

    def run(self) -> None:
        import time as _time

        backoff = 0.2
        while not self.stop.is_set():
            t0 = _time.monotonic()
            try:
                if not self.listed.is_set():
                    self._list()
                if self.crd_missing:
                    confirmed = not (
                        self._known and self._missing_streak < 2
                    )
                    if confirmed and len(self.paths) > 1:
                        # Probe the next served version of this CRD
                        # (v1alpha1 → v1alpha2 → …) before waiting out
                        # a full discovery period: a cluster that only
                        # installed the other version answers the very
                        # next LIST.
                        self._path_i = (
                            self._path_i + 1
                        ) % len(self.paths)
                        self.path = self.paths[self._path_i]
                        log.info("%s: probing %s", self.kind, self.path)
                        # A full sweep through every version without an
                        # answer = genuinely not installed: back off for
                        # the normal discovery period; versions not yet
                        # probed THIS sweep go quickly.  Counted, not
                        # `_path_i == 0`: a reflector that converged on
                        # a non-zero index starts its sweeps there.
                        self._probes_this_sweep += 1
                        wait = (
                            0.5
                            if self._probes_this_sweep % len(self.paths)
                            else self.CRD_RETRY_S
                        )
                    else:
                        # Wait out the discovery period (short when an
                        # unconfirmed blip still holds live state);
                        # the loop top's single _list() call site
                        # retries (the watch would just 404 too).
                        wait = 2.0 if not confirmed else self.CRD_RETRY_S
                    if self.stop.wait(wait):
                        return
                    self.listed.clear()
                    continue
                if self._watch_once():
                    self.relists += 1
                    self.listed.clear()  # 410: full re-list next loop
            except Exception as exc:  # noqa: BLE001 — reflectors retry
                if self.stop.is_set():
                    return
                log.warning("%s reflector error: %s (retrying)",
                            self.kind, exc)
            # Backoff covers EVERY fast turnaround, not just raised
            # errors: a persistently-410ing or instantly-dropping
            # apiserver must not be hammered by 7 hot re-list loops
            # (client-go backs off here too).  A watch that survived a
            # while resets the clock.
            if _time.monotonic() - t0 >= 5.0:
                backoff = 0.2
            else:
                if self.stop.wait(backoff):
                    return
                backoff = min(backoff * 2, 10.0)


class HttpWatchMux:
    """One reflector per resource, multiplexed into a line iterable the
    `K8sWatchAdapter` consumes as its reader.  SYNC is emitted once
    after every resource's initial LIST (≙ WaitForCacheSync)."""

    def __init__(
        self,
        client: _Client,
        resources: tuple[tuple[str, str], ...] = DEFAULT_RESOURCES,
    ) -> None:
        self.client = client
        self._sink: "queue.Queue[str | None]" = queue.Queue()
        self._stop = threading.Event()
        self.reflectors = [
            Reflector(client, kind, path, self._sink, self._stop)
            for kind, path in resources
        ]
        self._threads: list[threading.Thread] = []

    def start(self) -> "HttpWatchMux":
        for r in self.reflectors:
            t = threading.Thread(
                target=r.run, name=f"reflector-{r.kind}", daemon=True
            )
            self._threads.append(t)
            t.start()
        threading.Thread(target=self._sync_when_listed,
                         daemon=True).start()
        return self

    def _sync_when_listed(self) -> None:
        for r in self.reflectors:
            while not r.listed.wait(0.5):
                if self._stop.is_set():
                    return
        self._sink.put(json.dumps({"type": "SYNC"}))

    def served_api_version(self, kind: str) -> str:
        """group/version of the path `kind`'s reflector currently
        serves from (e.g. "scheduling.incubator.k8s.io/v1alpha2") —
        the version the WRITE side must target."""
        for r in self.reflectors:
            if r.kind == kind:
                parts = r.path.split("/")
                if len(parts) >= 4 and parts[1] == "apis":
                    return f"{parts[2]}/{parts[3]}"
        from kube_batch_tpu.client.k8s_write import PODGROUP_API_VERSION

        return PODGROUP_API_VERSION

    def close(self) -> None:
        """Stop every reflector and end the line iterator (the adapter
        sees EOF, exactly like a dropped stream)."""
        self._stop.set()
        self._sink.put(None)

    def __iter__(self) -> Iterator[str]:
        while True:
            line = self._sink.get()
            if line is None:
                return
            yield line


class K8sHttpBackend:
    """Binder/Evictor/StatusUpdater/EventSink over real HTTP, issuing
    the exact shapes of client/k8s_write.py as REST calls (create →
    POST, delete → DELETE, update → PUT).  Raises on non-2xx, which
    the cache's bind/evict funnel turns into resync/rollback.

    Writes use ONE kept-alive connection PER THREAD (thread-local,
    reopened on error): no TCP+TLS setup per Binding POST, and no
    shared-connection lock either — the session's bind fan-out
    (Session.BIND_WORKERS threads) must genuinely overlap its round
    trips, or a 47.5k-pod gang commit at tunnel latencies serializes
    right back to the hour the pool exists to prevent."""

    _METHODS = {
        "create": "POST", "delete": "DELETE", "update": "PUT",
        "patch": "PATCH",
    }

    def __init__(self, client: _Client) -> None:
        self.client = client
        # -- leadership fencing (doc/design/failover-fencing.md) --------
        # A real apiserver cannot enforce fencing epochs on Binding
        # POSTs without an admission webhook, so the HTTP dialect's
        # fencing is CLIENT-side only: the epoch (mapped from the
        # Lease's spec.leaseTransitions by _HttpLeaseLock) is tracked,
        # and a local fence set at stand-down fails data-plane writes
        # fast so a deposed leader's flush workers stop writing the
        # moment the loss is observed.
        self._epoch: int | None = None
        self._fenced = False
        import collections
        import time

        # Wall-clock seeded: event names must not collide across
        # restarts (a real apiserver 409s duplicate names).
        self._event_seq = time.time_ns()
        self._event_lock = threading.Lock()
        self._local = threading.local()
        # Events post from ONE flusher thread, never the caller's (≙
        # the async client-go recorder, and the same design as
        # K8sStreamBackend): diagnosis can emit hundreds of Events per
        # cycle, and at tunnel RTTs synchronous POSTs on the cycle
        # thread would dwarf the 1 s period.  Bounded: overflow sheds
        # oldest (events are best-effort).
        self._event_q: collections.deque[dict] = collections.deque(
            maxlen=1000
        )
        self._event_ready = threading.Event()
        self._event_flusher = threading.Thread(
            target=self._flush_events, daemon=True
        )
        self._event_flusher.start()
        # The PodGroup CRD version writes must target (a v1alpha2-only
        # apiserver 404s a v1alpha1 status PUT).  Replaced with the
        # mux's discovered-version getter by follow_served_versions();
        # standalone backends keep the v1alpha1 default.
        from kube_batch_tpu.client.k8s_write import PODGROUP_API_VERSION

        self.pod_group_api_version = lambda: PODGROUP_API_VERSION

    def follow_served_versions(self, mux: "HttpWatchMux") -> None:
        """Thread the reflectors' served-version discovery into the
        write path: status PUTs follow wherever the PodGroup LIST
        actually converged (version rotation happens at runtime, so
        this is a live getter, not a snapshot)."""
        self.pod_group_api_version = (
            lambda: mux.served_api_version("PodGroup")
        )

    def _flush_events(self) -> None:
        while True:
            self._event_ready.wait(0.5)
            self._event_ready.clear()
            while True:
                try:
                    req = self._event_q.popleft()
                except IndexError:
                    break
                try:
                    self._issue(req)
                except HttpError as exc:
                    if 400 <= exc.status < 500 and exc.status not in (
                        408, 429,  # timeouts/throttling are retryable
                    ):
                        # Permanent rejection (RBAC denial, invalid
                        # object): re-queueing would wedge the whole
                        # pipeline behind one poison event — drop it
                        # and keep posting the rest.
                        log.debug("event rejected (%d), dropped: %s",
                                  exc.status, exc)
                        continue
                    self._event_q.appendleft(req)  # transient: keep it
                    break
                except Exception as exc:  # noqa: BLE001 — transport down
                    # Keep the backlog across an apiserver outage:
                    # re-queue and retry on the next wakeup instead of
                    # serially burning a timeout per queued event and
                    # discarding them all.  appendleft on a full ring
                    # sheds the newest instead of the oldest — fine,
                    # shedding SOMETHING is the bounded queue's job.
                    self._event_q.appendleft(req)
                    log.debug("event post failed (kept queued): %s", exc)
                    break

    def drain_events(self, timeout: float = 5.0) -> bool:
        """Best-effort blocking flush for process teardown: events
        recorded by the FINAL cycle (evictions, unschedulable
        diagnoses) would otherwise die with the daemon flusher thread.
        Returns True when the queue emptied in time."""
        import time as _time

        deadline = _time.monotonic() + timeout
        self._event_ready.set()
        while self._event_q and _time.monotonic() < deadline:
            _time.sleep(0.05)
            self._event_ready.set()
        return not self._event_q

    def _conn_get(self) -> tuple[http.client.HTTPConnection, bool]:
        """(connection, fresh) for THIS thread."""
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = self.client.connect()
            self._local.conn = conn
            return conn, True
        return conn, False

    def _drop_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        try:
            if conn is not None:
                conn.close()
        except Exception:  # noqa: BLE001
            pass
        self._local.conn = None

    def _issue(self, req: dict) -> None:
        method = self._METHODS[req["verb"]]
        path = self.client.prefix + req["path"]
        payload = json.dumps(req["object"])
        headers = self.client._headers({
            # PATCH carries a merge patch (the cordon write's partial
            # spec); everything else posts/puts whole objects.
            "Content-Type": (
                "application/merge-patch+json"
                if method == "PATCH" else "application/json"
            ),
        })
        for attempt in (1, 2):
            conn, fresh = self._conn_get()
            try:
                conn.request(
                    method, path, body=payload, headers=headers
                )
            except (OSError, http.client.HTTPException):
                # Failed to SEND: the server never saw it — always
                # safe to retry, even for non-idempotent verbs.
                self._drop_conn()
                if attempt == 2:
                    raise
                continue
            try:
                resp = conn.getresponse()
                data = resp.read().decode("utf-8", "replace")
            except http.client.RemoteDisconnected:
                self._drop_conn()
                if not fresh and attempt == 1:
                    # A REUSED keep-alive closed with zero response
                    # bytes: the server shut the idle connection
                    # before reading the request — retry on a
                    # fresh one.  (A fresh connection dying here is
                    # ambiguous: the write may have LANDED, and
                    # blindly re-POSTing a Binding would 409 and
                    # roll back a bind that succeeded — surface it
                    # instead; the resync/watch paths reconcile.)
                    continue
                raise ConnectionError(
                    f"response lost for {method} {path}"
                )
            except (OSError, http.client.HTTPException) as exc:
                self._drop_conn()
                raise ConnectionError(
                    f"response lost for {method} {path}: {exc}"
                ) from exc
            if resp.status >= 300:
                raise HttpError(resp.status, data)
            return

    def ping(self) -> None:
        """Cheapest possible round trip — the guardrail breaker's
        half-open probe (guardrails.Guardrails.pre_cycle).  GET
        /version touches no resources and answers on any live
        apiserver; any response at all proves the wire recovered.
        Never fenced: the probe is how a standby watches for heal."""
        self.client.request_json("GET", "/version")

    # -- operational-state mirror (kube_batch_tpu/statestore/) ----------
    def put_state_snapshot(self, payload: dict) -> None:
        """The statestore's HA mirror as a real ConfigMap write: PUT
        the named object, falling back to a collection POST when it
        does not exist yet (k8s update-then-create).  Client-side
        fenced like the other HTTP writes (a real apiserver cannot
        reject by epoch without a webhook)."""
        from kube_batch_tpu.client.k8s_write import (
            STATE_CONFIGMAP_NAMESPACE,
            state_snapshot_request,
        )

        self._check_fence()
        req = state_snapshot_request(payload)
        try:
            self._issue(req)
        except HttpError as exc:
            if exc.status != 404:
                raise
            self._issue({
                "verb": "create",
                "path": (
                    f"/api/v1/namespaces/{STATE_CONFIGMAP_NAMESPACE}"
                    "/configmaps"
                ),
                "object": req["object"],
            })

    def get_state_snapshot(self) -> dict | None:
        """The mirrored snapshot read back from the ConfigMap, or None
        when absent/unparsable (a cold mirror means 'start blind',
        never a crash — the caller treats None as no peer state)."""
        from kube_batch_tpu.client.k8s_write import STATE_CONFIGMAP_PATH

        try:
            obj = self.client.request_json("GET", STATE_CONFIGMAP_PATH)
            raw = (obj.get("data") or {}).get("state")
            payload = json.loads(raw) if isinstance(raw, str) else None
        except (HttpError, json.JSONDecodeError):
            return None
        return payload if isinstance(payload, dict) else None

    # -- AOT compile-artifact mirror (compile_cache.ArtifactBank) -------
    def put_compile_artifact(self, payload: dict) -> None:
        """One bank entry merge-PATCHed into the compile-artifacts
        ConfigMap (create-on-404 like the statestore mirror).
        Client-side fenced like the other HTTP writes
        (doc/design/compile-artifacts.md)."""
        from kube_batch_tpu.client.k8s_write import (
            COMPILE_CONFIGMAP_NAMESPACE,
            compile_artifact_request,
        )

        self._check_fence()
        req = compile_artifact_request(payload)
        try:
            self._issue(req)
        except HttpError as exc:
            if exc.status != 404:
                raise
            self._issue({
                "verb": "create",
                "path": (
                    f"/api/v1/namespaces/{COMPILE_CONFIGMAP_NAMESPACE}"
                    "/configmaps"
                ),
                "object": req["object"],
            })

    def get_compile_artifact(self) -> list:
        """Every mirrored bank entry read back from the ConfigMap
        (possibly empty — a cold mirror means 'compile fresh', never
        a crash).  Unparsable values are skipped; the bank's own
        validation chain re-checks every survivor before any
        deserialization."""
        from kube_batch_tpu.client.k8s_write import COMPILE_CONFIGMAP_PATH
        from kube_batch_tpu.compile_cache import payloads_from_configmap_data

        try:
            obj = self.client.request_json("GET", COMPILE_CONFIGMAP_PATH)
            data = obj.get("data") or {}
        except HttpError:
            return []
        return payloads_from_configmap_data(data)

    # -- leadership fencing (same surface as StreamBackend) -------------
    @property
    def epoch(self) -> int | None:
        return self._epoch

    def set_epoch(self, epoch: int | None) -> None:
        self._epoch = epoch
        self._fenced = False

    def fence(self) -> None:
        self._fenced = True

    # -- cell scoping (same surface as StreamBackend) -------------------
    # A real apiserver cannot reject Binding POSTs by cell without an
    # admission webhook, so — exactly like the HTTP epoch fence — the
    # CLIENT-side half is the load-bearing one here: the cell-scoped
    # watch filter keeps foreign objects out of the mirror, and the
    # local fence below fast-fails any bind that still names a
    # foreign node.
    _cell: str | None = None
    cell_of_node = None  # resolver installed by the CLI wiring

    @property
    def cell(self) -> str | None:
        return self._cell

    def set_cell(self, cell: str | None) -> None:
        self._cell = cell or None

    def check_cell_target(self, node_name: str) -> None:
        from kube_batch_tpu.client.adapter import StreamBackend

        StreamBackend.check_cell_target(self, node_name)

    def _check_fence(self) -> None:
        if self._fenced:
            from kube_batch_tpu import metrics, trace
            from kube_batch_tpu.client.adapter import StaleEpochError

            metrics.stale_epoch_writes.inc()
            trace.note_transition("stale-epoch", where="http-local-fence")
            raise StaleEpochError(
                "write fenced locally: leadership lost (stand-down); "
                "awaiting re-acquire"
            )

    def bind(self, pod: Pod, node_name: str) -> None:
        self._check_fence()
        self.check_cell_target(node_name)
        self._issue(binding_request(pod, node_name))

    def evict(self, pod: Pod, reason: str) -> None:
        self._check_fence()
        self._issue(evict_request(pod))

    def update_pod_group(self, group: PodGroup) -> None:
        self._check_fence()
        self._issue(pod_group_status_request(
            group, api_version=self.pod_group_api_version(),
        ))

    def cordon_node(self, name: str, unschedulable: bool) -> None:
        """Mirror a ledger/manual cordon onto the node's
        spec.unschedulable with a merge PATCH (≙ kubectl cordon)."""
        from kube_batch_tpu.client.k8s_write import (
            node_unschedulable_request,
        )

        self._check_fence()
        self._issue(node_unschedulable_request(name, unschedulable))

    def record_event(
        self, kind: str, name: str, reason: str, message: str,
        count: int = 1, namespace: str = "default",
    ) -> None:
        if self._fenced:
            # Deposed: drop, same as K8sStreamBackend — the successor
            # narrates the world from here on, and the HTTP dialect's
            # fence is client-side only, so the async flusher must not
            # keep POSTing a dead epoch's events.
            return
        with self._event_lock:
            self._event_seq += 1
            seq = self._event_seq
        self._event_q.append(event_request(
            kind, name, reason, message,
            count=count, namespace=namespace, sequence=seq,
            pod_group_api_version=self.pod_group_api_version(),
        ))
        self._event_ready.set()


class _HttpLeaseLock:
    """The resourcelock primitive over a coordination.k8s.io/v1 Lease
    (≙ client-go's LeaseLock), consumed by the shared `LeaseElector`
    state machine: acquire/renew raise when the lease is held, with
    apiserver optimistic concurrency (a 409 on update = lost the race).

    Expiry is judged by LOCAL observation, never by comparing clocks
    across hosts: the remote renewTime is only a CHANGE detector — a
    lease counts as expired when the SAME renewTime has been observed
    locally for longer than leaseDurationSeconds (client-go's
    observedTime dance).  Cross-host clock skew therefore cannot cause
    a wrongful steal from a live leader."""

    def __init__(
        self,
        client: _Client,
        name: str = "kube-batch-tpu",
        namespace: str = "kube-system",
    ) -> None:
        self.client = client
        self.name = name
        self.namespace = namespace
        self.path = (
            f"/apis/coordination.k8s.io/v1/namespaces/{namespace}"
            f"/leases/{name}"
        )
        self.collection = (
            f"/apis/coordination.k8s.io/v1/namespaces/{namespace}/leases"
        )
        # (renewTime string last seen, local monotonic when first seen)
        self._observed: tuple[str | None, float] = (None, 0.0)
        #: Fencing epoch of the last successful take: mapped onto the
        #: Lease's spec.leaseTransitions (+1 so the first leader gets
        #: epoch 1, matching the wire dialect) — a takeover bumps
        #: transitions, so a re-contended epoch is strictly higher.
        self.last_epoch: int | None = None

    @staticmethod
    def _now() -> str:
        import datetime

        return datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%S.%fZ"
        )

    def _locally_expired(self, renew_time: str | None, ttl: float) -> bool:
        import time as _time

        seen, since = self._observed
        if renew_time != seen:
            # Fresh renewal observed: restart the local clock.
            self._observed = (renew_time, _time.monotonic())
            return False
        return _time.monotonic() - since > ttl

    def _try_take(self, holder: str, ttl: float,
                  renewal: bool = False) -> bool:
        """One CAS attempt; True when `holder` now holds the Lease.
        `renewal` distinguishes the renew loop's keep-alive (never
        bumps leaseTransitions) from an ACQUIRE: an acquire that finds
        the Lease still naming `holder` is a revival after a
        stand-down (the elector only re-enters acquire after a
        definitive loss), and must bump transitions — the wire
        dialect mints a fresh epoch for a revived-expired lease even
        by its previous holder, and the strictly-higher-epoch contract
        holds across transports."""
        from kube_batch_tpu.client.adapter import FatalElectionError

        try:
            try:
                lease = self.client.request_json("GET", self.path)
            except HttpError as exc:
                if exc.status in (401, 403):
                    raise FatalElectionError(
                        f"lease access denied ({exc.status}): check the "
                        f"token / RBAC on coordination.k8s.io leases"
                    ) from exc
                if exc.status != 404:
                    raise
                body = {
                    "apiVersion": "coordination.k8s.io/v1",
                    "kind": "Lease",
                    "metadata": {"name": self.name,
                                 "namespace": self.namespace},
                    "spec": {
                        "holderIdentity": holder,
                        "leaseDurationSeconds": int(ttl),
                        "acquireTime": self._now(),
                        "renewTime": self._now(),
                        "leaseTransitions": 0,
                    },
                }
                try:
                    self.client.request_json("POST", self.collection, body)
                    self.last_epoch = 1  # transitions 0 → first epoch
                    return True
                except HttpError as exc2:
                    if exc2.status == 409:
                        return False  # lost the creation race
                    raise
            spec = lease.get("spec") or {}
            current = spec.get("holderIdentity")
            if current and current != holder and not self._locally_expired(
                spec.get("renewTime"),
                float(spec.get("leaseDurationSeconds") or ttl),
            ):
                return False  # held by a live leader
            spec.update({
                "holderIdentity": holder,
                "leaseDurationSeconds": int(ttl),
                "renewTime": self._now(),
            })
            if current != holder or not renewal:
                # Change of hands, OR a non-renewal take by the
                # previous holder (revival after stand-down): new
                # writer incarnation, new epoch.
                spec["acquireTime"] = self._now()
                spec["leaseTransitions"] = int(
                    spec.get("leaseTransitions") or 0
                ) + 1
            lease["spec"] = spec
            try:
                self.client.request_json("PUT", self.path, lease)
                self.last_epoch = int(
                    spec.get("leaseTransitions") or 0
                ) + 1
                return True
            except HttpError as exc:
                if exc.status == 409:
                    return False  # lost the update race (stale RV)
                raise
        except FatalElectionError:
            raise
        except HttpError as exc:
            if exc.status in (401, 403):
                raise FatalElectionError(
                    f"lease access denied ({exc.status})"
                ) from exc
            # Other apiserver answers are transient for election
            # purposes — but must NOT look like a definitive "lease
            # lost" (RuntimeError) to the renew loop.
            raise ConnectionError(str(exc)) from exc

    # -- the backend protocol LeaseElector consumes ---------------------
    def acquire_lease(self, holder: str, ttl: float) -> int | None:
        if not self._try_take(holder, ttl):
            raise ConnectionError("lease held by the current leader")
        return self.last_epoch

    def renew_lease(self, holder: str, ttl: float) -> None:
        if not self._try_take(holder, ttl, renewal=True):
            # Definitive: another identity owns an unexpired Lease
            # (RuntimeError = the renew loop's stand-down signal).
            raise RuntimeError(f"lease lost by {holder}")

    def release_lease(self, holder: str) -> None:
        try:
            lease = self.client.request_json("GET", self.path)
        except HttpError:
            return
        if (lease.get("spec") or {}).get("holderIdentity") == holder:
            lease["spec"]["holderIdentity"] = ""
            self.client.request_json("PUT", self.path, lease)


def HttpLeaseElector(
    client: _Client,
    holder: str,
    name: str = "kube-batch-tpu",
    namespace: str = "kube-system",
    ttl: float = 15.0,
    retry_period: float | None = None,
    fence_backend=None,
):
    """Leader election on a coordination/v1 Lease: the shared
    `LeaseElector` machinery (acquire loop, renew deadline, stand-down,
    release) over the `_HttpLeaseLock` primitive — one election state
    machine for both transports, differing only in the resourcelock
    (≙ client-go's leaderelection / resourcelock split).
    `fence_backend` (a K8sHttpBackend) is stamped with the acquired
    epoch (mapped from leaseTransitions) and fenced on loss — the lock
    primitive here is NOT the write backend, unlike the stream
    transport, so the pairing must be explicit."""
    from kube_batch_tpu.client.adapter import LeaseElector

    elector = LeaseElector(
        _HttpLeaseLock(client, name, namespace), holder,
        ttl=ttl, retry_period=retry_period,
        fence_backend=fence_backend,
    )
    elector.name = name
    return elector
