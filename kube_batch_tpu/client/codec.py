"""JSON codec for the framework-native API objects.

Reference counterpart: the generated deep-copy/serialization machinery
of pkg/apis/scheduling/v1alpha1 + core/v1 as used by client-go.  Field
names follow the Kubernetes-flavored camelCase the reference's YAML
uses, so a world file and a wire object read the same.
"""

from __future__ import annotations

from typing import Any

from kube_batch_tpu.api.types import (
    PodGroupCondition,
    PodGroupPhase,
    TaskStatus,
)
from kube_batch_tpu.cache.cluster import (
    Claim,
    Namespace,
    Node,
    Pod,
    PodDisruptionBudget,
    PodGroup,
    Queue,
    StorageClass,
)


def encode_pod(pod: Pod) -> dict[str, Any]:
    return {
        "uid": pod.uid,
        "name": pod.name,
        "namespace": pod.namespace,
        "group": pod.group,
        "request": dict(pod.request),
        "priority": pod.priority,
        "selector": dict(pod.selector),
        "labels": dict(pod.labels),
        "affinity": sorted(pod.affinity),
        "antiAffinity": sorted(pod.anti_affinity),
        "podPrefs": dict(pod.pod_prefs),
        "preferences": dict(pod.preferences),
        "tolerations": sorted(pod.tolerations),
        "ports": sorted(pod.ports),
        "claims": sorted(pod.claims),
        "status": pod.status.name,
        "node": pod.node,
        "creation": pod.creation,
    }


# Wire/YAML keys each object may carry.  The single source of truth —
# the CLI's world-file loader validates against these same sets, so a
# new field needs exactly one decoder change.
POD_KEYS = frozenset({
    "uid", "name", "namespace", "group", "request", "priority", "selector",
    "labels", "affinity", "antiAffinity", "podPrefs", "preferences",
    "tolerations", "ports", "claims", "status", "node", "creation",
})
NODE_KEYS = frozenset({
    "uid", "name", "allocatable", "labels", "taints", "ready",
    "memoryPressure", "diskPressure", "pidPressure",
    "unschedulable", "conditions",
})
CLAIM_KEYS = frozenset({"uid", "name", "storageClass", "boundNode"})
STORAGE_CLASS_KEYS = frozenset({"uid", "name", "allowedNodeLabels"})
PDB_KEYS = frozenset({
    "uid", "name", "minAvailable", "minAvailablePct",
    "maxUnavailable", "maxUnavailablePct", "selector",
})
NAMESPACE_KEYS = frozenset({"uid", "name", "weight"})


def decode_pod(d: dict[str, Any]) -> Pod:
    """Wire dict → Pod.  `uid`/`creation` are optional: absent (fresh
    YAML objects), the Pod defaults allocate them in arrival order."""
    kwargs: dict[str, Any] = {}
    if "uid" in d:
        kwargs["uid"] = d["uid"]
    if "creation" in d:
        kwargs["creation"] = int(d["creation"])
    return Pod(
        name=d["name"],
        namespace=d.get("namespace", "default"),
        group=d.get("group"),
        request=dict(d.get("request", {})),
        priority=int(d.get("priority", 0)),
        selector=dict(d.get("selector", {})),
        labels=dict(d.get("labels", {})),
        affinity=frozenset(d.get("affinity", [])),
        anti_affinity=frozenset(d.get("antiAffinity", [])),
        pod_prefs=dict(d.get("podPrefs", {})),
        preferences=dict(d.get("preferences", {})),
        tolerations=frozenset(d.get("tolerations", [])),
        ports=frozenset(int(p) for p in d.get("ports", [])),
        claims=frozenset(d.get("claims", [])),
        status=TaskStatus[d.get("status", "PENDING")],
        node=d.get("node"),
        **kwargs,
    )


def encode_node(node: Node) -> dict[str, Any]:
    return {
        "uid": node.uid,
        "name": node.name,
        "allocatable": dict(node.allocatable),
        "labels": dict(node.labels),
        "taints": sorted(node.taints),
        "ready": node.ready,
        "memoryPressure": node.memory_pressure,
        "diskPressure": node.disk_pressure,
        "pidPressure": node.pid_pressure,
        "unschedulable": node.unschedulable,
        "conditions": dict(node.conditions),
    }


def decode_node(d: dict[str, Any]) -> Node:
    kwargs: dict[str, Any] = {}
    if "uid" in d:
        kwargs["uid"] = d["uid"]
    return Node(
        name=d["name"],
        allocatable=dict(d.get("allocatable", {})),
        labels=dict(d.get("labels", {})),
        taints=frozenset(d.get("taints", [])),
        ready=bool(d.get("ready", True)),
        memory_pressure=bool(d.get("memoryPressure", False)),
        disk_pressure=bool(d.get("diskPressure", False)),
        pid_pressure=bool(d.get("pidPressure", False)),
        unschedulable=bool(d.get("unschedulable", False)),
        conditions={
            str(k): bool(v)
            for k, v in (d.get("conditions") or {}).items()
        },
        **kwargs,
    )


def encode_pod_group(group: PodGroup) -> dict[str, Any]:
    return {
        "uid": group.uid,
        "name": group.name,
        "queue": group.queue,
        "minMember": group.min_member,
        "priority": group.priority,
        "phase": group.phase.name,
        "running": group.running,
        "succeeded": group.succeeded,
        "failed": group.failed,
        "conditions": [
            {
                "type": c.type, "status": c.status,
                "reason": c.reason, "message": c.message,
            }
            if isinstance(c, PodGroupCondition)
            else {"type": "Note", "message": str(c)}
            for c in group.conditions
        ],
        "creation": group.creation,
    }


def decode_pod_group(d: dict[str, Any]) -> PodGroup:
    return PodGroup(
        uid=d["uid"],
        name=d["name"],
        queue=d.get("queue", ""),
        min_member=int(d.get("minMember", 1)),
        priority=int(d.get("priority", 0)),
        phase=PodGroupPhase[d.get("phase", "PENDING")],
        running=int(d.get("running", 0)),
        succeeded=int(d.get("succeeded", 0)),
        failed=int(d.get("failed", 0)),
        conditions=[
            PodGroupCondition(
                type=c.get("type", "Note"),
                status=bool(c.get("status", True)),
                reason=c.get("reason", ""),
                message=c.get("message", ""),
            )
            if isinstance(c, dict)
            else PodGroupCondition(type="Note", message=str(c))
            for c in d.get("conditions", [])
        ],
        creation=int(d["creation"]) if "creation" in d else 0,
    )


def encode_queue(queue: Queue) -> dict[str, Any]:
    out = {"uid": queue.uid, "name": queue.name, "weight": queue.weight}
    if queue.cell:
        # Only celled queues carry the key: uncelled fleets' wire
        # shapes (and recorded chaos traces) stay byte-identical.
        out["cell"] = queue.cell
    return out


def decode_queue(d: dict[str, Any]) -> Queue:
    return Queue(
        uid=d["uid"], name=d["name"], weight=float(d.get("weight", 1.0)),
        cell=str(d.get("cell", "")),
    )


def encode_claim(claim: Claim) -> dict[str, Any]:
    return {
        "uid": claim.uid,
        "name": claim.name,
        "storageClass": claim.storage_class,
        "boundNode": claim.bound_node,
    }


def decode_claim(d: dict[str, Any]) -> Claim:
    kwargs = {"uid": d["uid"]} if "uid" in d else {}
    return Claim(
        name=d["name"],
        storage_class=d.get("storageClass", ""),
        bound_node=d.get("boundNode"),
        **kwargs,
    )


def encode_storage_class(sc: StorageClass) -> dict[str, Any]:
    return {
        "uid": sc.uid,
        "name": sc.name,
        "allowedNodeLabels": sorted(sc.allowed_node_labels),
    }


def decode_storage_class(d: dict[str, Any]) -> StorageClass:
    kwargs = {"uid": d["uid"]} if "uid" in d else {}
    return StorageClass(
        name=d["name"],
        allowed_node_labels=frozenset(d.get("allowedNodeLabels", [])),
        **kwargs,
    )


def encode_namespace(ns: Namespace) -> dict[str, Any]:
    return {"uid": ns.uid, "name": ns.name, "weight": ns.weight}


def decode_namespace(d: dict[str, Any]) -> Namespace:
    kwargs = {"uid": d["uid"]} if "uid" in d else {}
    return Namespace(
        name=d["name"], weight=float(d.get("weight", 1.0)), **kwargs
    )


def encode_pdb(pdb: PodDisruptionBudget) -> dict[str, Any]:
    out: dict[str, Any] = {
        "uid": pdb.uid,
        "name": pdb.name,
        "minAvailable": pdb.min_available,
        "selector": dict(pdb.selector),
    }
    if pdb.min_available_pct is not None:
        out["minAvailablePct"] = pdb.min_available_pct
    if pdb.max_unavailable is not None:
        out["maxUnavailable"] = pdb.max_unavailable
    if pdb.max_unavailable_pct is not None:
        out["maxUnavailablePct"] = pdb.max_unavailable_pct
    return out


def decode_pdb(d: dict[str, Any]) -> PodDisruptionBudget:
    kwargs: dict[str, Any] = {"uid": d["uid"]} if "uid" in d else {}
    for wire, field in (("minAvailablePct", "min_available_pct"),
                        ("maxUnavailable", "max_unavailable"),
                        ("maxUnavailablePct", "max_unavailable_pct")):
        if d.get(wire) is not None:
            kwargs[field] = d[wire]
    return PodDisruptionBudget(
        name=d["name"],
        min_available=int(d.get("minAvailable", 0)),
        selector=dict(d.get("selector", {})),
        **kwargs,
    )


ENCODERS = {
    "Pod": encode_pod,
    "Node": encode_node,
    "PodGroup": encode_pod_group,
    "Queue": encode_queue,
    "PersistentVolumeClaim": encode_claim,
    "StorageClass": encode_storage_class,
    "Namespace": encode_namespace,
    "PodDisruptionBudget": encode_pdb,
}
DECODERS = {
    "Pod": decode_pod,
    "Node": decode_node,
    "PodGroup": decode_pod_group,
    "Queue": decode_queue,
    "PersistentVolumeClaim": decode_claim,
    "StorageClass": decode_storage_class,
    "Namespace": decode_namespace,
    "PodDisruptionBudget": decode_pdb,
}
