"""ExternalCluster: an authoritative out-of-process-shaped cluster.

The stand-in for a real apiserver+kubelets in adapter tests and demos
(≙ the role a kind/minikube cluster plays for the reference's e2e suite,
test/e2e/util.go · initTestContext).  It owns the truth about pods,
nodes, groups and queues, serves the JSON-lines wire protocol over a
duplex stream, and reacts to scheduler writes the way a cluster would:

* bind   → pod becomes Bound on the node (MODIFIED event), unless the
           node is gone or a failure is injected → error response;
* evict  → pod returns to Pending (MODIFIED event) — the controller
           recreating the workload, like the in-process simulator;
* tick() → Bound pods start Running (kubelet heartbeat analog).

The scheduler side never touches this object directly — everything
crosses the wire, so a test that passes here proves the adapter path
end-to-end (VERDICT r1 item 4: schedule a world the scheduler only
learns about through the stream).
"""

from __future__ import annotations

import json
import socket
import threading
from typing import IO

from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.cache.cluster import Node, Pod, PodGroup, Queue
from kube_batch_tpu.client.codec import (
    encode_node,
    encode_pod,
    encode_pod_group,
    encode_queue,
)


def stream_pair() -> tuple[IO[str], IO[str], IO[str], IO[str]]:
    """(cluster_r, cluster_w, scheduler_r, scheduler_w) over a local
    socketpair — the two ends of the 'network'."""
    a, b = socket.socketpair()
    return (
        a.makefile("r", encoding="utf-8"),
        a.makefile("w", encoding="utf-8"),
        b.makefile("r", encoding="utf-8"),
        b.makefile("w", encoding="utf-8"),
    )


class ExternalCluster:
    def __init__(self, reader: IO[str], writer: IO[str]) -> None:
        self._reader = reader
        self._writer = writer
        self._lock = threading.RLock()
        self.pods: dict[str, Pod] = {}
        self.nodes: dict[str, Node] = {}
        self.groups: dict[str, PodGroup] = {}
        self.queues: dict[str, Queue] = {}
        self.binds: list[tuple[str, str]] = []
        self.evictions: list[tuple[str, str]] = []
        self.status_updates: list[PodGroup] = []
        self.fail_bind_pods: set[str] = set()  # inject failures by pod name
        self._thread: threading.Thread | None = None

    # -- wire out -------------------------------------------------------
    def _emit(self, mtype: str, kind: str, obj: dict) -> None:
        with self._lock:
            self._writer.write(
                json.dumps({"type": mtype, "kind": kind, "object": obj}) + "\n"
            )
            self._writer.flush()

    def _respond(self, rid: int, ok: bool, error: str = "") -> None:
        msg: dict = {"type": "RESPONSE", "id": rid, "ok": ok}
        if error:
            msg["error"] = error
        with self._lock:
            self._writer.write(json.dumps(msg) + "\n")
            self._writer.flush()

    def sync(self) -> None:
        """Mark the initial LIST replay complete (≙ informer HasSynced)."""
        with self._lock:
            self._writer.write(json.dumps({"type": "SYNC"}) + "\n")
            self._writer.flush()

    # -- authoritative world mutations (all emit watch events) ----------
    def add_node(self, node: Node) -> None:
        with self._lock:
            self.nodes[node.name] = node
            self._emit("ADDED", "Node", encode_node(node))

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self.nodes.pop(name, None)
            if node is None:
                return
            # Pods on the dead node go Pending again (controller restart).
            for pod in self.pods.values():
                if pod.node == name:
                    pod.node = None
                    pod.status = TaskStatus.PENDING
                    self._emit("MODIFIED", "Pod", encode_pod(pod))
            self._emit("DELETED", "Node", encode_node(node))

    def add_queue(self, queue: Queue) -> None:
        with self._lock:
            self.queues[queue.name] = queue
            self._emit("ADDED", "Queue", encode_queue(queue))

    def submit(self, group: PodGroup, pods: list[Pod]) -> None:
        with self._lock:
            self.groups[group.name] = group
            self._emit("ADDED", "PodGroup", encode_pod_group(group))
            for pod in pods:
                pod.group = group.name
                self.pods[pod.uid] = pod
                self._emit("ADDED", "Pod", encode_pod(pod))

    def tick(self) -> None:
        """Bound → Running (kubelet starting containers)."""
        with self._lock:
            for pod in self.pods.values():
                if pod.status == TaskStatus.BOUND:
                    pod.status = TaskStatus.RUNNING
                    self._emit("MODIFIED", "Pod", encode_pod(pod))

    # -- the serve loop (scheduler write requests) ----------------------
    def start(self) -> "ExternalCluster":
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def _serve(self) -> None:
        try:
            for line in self._reader:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue  # one garbled request must not kill serving
                if msg.get("type") != "REQUEST":
                    continue
                self._handle(msg)
        except (OSError, ValueError):
            # ValueError = iterating a concurrently-closed file object;
            # JSONDecodeError never reaches here (handled per line).
            pass  # scheduler hung up

    def _handle(self, msg: dict) -> None:
        verb, rid = msg.get("verb"), msg["id"]
        with self._lock:
            if verb == "bind":
                pod = self.pods.get(msg["pod"])
                if pod is None:
                    self._respond(rid, False, "pod not found")
                elif pod.name in self.fail_bind_pods:
                    self._respond(rid, False, "injected bind failure")
                elif msg["node"] not in self.nodes:
                    self._respond(rid, False, f"node {msg['node']} not found")
                else:
                    pod.node = msg["node"]
                    pod.status = TaskStatus.BOUND
                    self.binds.append((pod.name, msg["node"]))
                    self._respond(rid, True)
                    self._emit("MODIFIED", "Pod", encode_pod(pod))
            elif verb == "evict":
                pod = self.pods.get(msg["pod"])
                if pod is None:
                    self._respond(rid, False, "pod not found")
                else:
                    pod.node = None
                    pod.status = TaskStatus.PENDING
                    self.evictions.append((pod.name, msg.get("reason", "")))
                    self._respond(rid, True)
                    self._emit("MODIFIED", "Pod", encode_pod(pod))
            elif verb == "updatePodGroup":
                from kube_batch_tpu.client.codec import decode_pod_group

                group = decode_pod_group(msg["object"])
                if group.name in self.groups:
                    self.groups[group.name] = group
                self.status_updates.append(group)
                self._respond(rid, True)
            else:
                self._respond(rid, False, f"unknown verb {verb}")
