"""ExternalCluster: an authoritative out-of-process-shaped cluster.

The stand-in for a real apiserver+kubelets in adapter tests and demos
(≙ the role a kind/minikube cluster plays for the reference's e2e suite,
test/e2e/util.go · initTestContext).  It owns the truth about pods,
nodes, groups and queues, serves the JSON-lines wire protocol over a
duplex stream, and reacts to scheduler writes the way a cluster would:

* bind   → pod becomes Bound on the node (MODIFIED event), unless the
           node is gone or a failure is injected → error response;
* evict  → pod returns to Pending (MODIFIED event) — the controller
           recreating the workload, like the in-process simulator;
* tick() → Bound pods start Running (kubelet heartbeat analog);
* lease verbs (acquire/renew/release with TTL) → the resourcelock of
  the reference's leader election (app/server.go · leaderelection.
  RunOrDie): the lock object lives on the CLUSTER, so standbys on
  other hosts contend for it over the wire (VERDICT r3 next #5).
  Every acquire that changes hands (or revives an expired lease)
  MINTS a monotonically increasing fencing EPOCH, returned in the
  response (≙ the Lease's ``spec.leaseTransitions``); data-plane
  writes carrying an ``epoch`` field are REJECTED with a structured
  ``StaleEpoch`` error unless it matches the current epoch — a
  deposed leader's in-flight flush workers can never land zombie
  writes after a successor takes over
  (doc/design/failover-fencing.md).

Multiple scheduler sessions may attach (leader + standbys, like
replicas sharing one apiserver); watch events broadcast to all of
them, and a late-attaching session gets a LIST replay first
(≙ informer re-list on connect — stateless recovery).

The scheduler side never touches this object directly — everything
crosses the wire, so a test that passes here proves the adapter path
end-to-end (VERDICT r1 item 4: schedule a world the scheduler only
learns about through the stream).
"""

from __future__ import annotations

import collections
import json
import socket
import threading
import time
from typing import IO

from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.cache.cluster import Node, Pod, PodGroup, Queue
from kube_batch_tpu.client.codec import (
    encode_node,
    encode_pod,
    encode_pod_group,
    encode_queue,
)


def stream_pair() -> tuple[IO[str], IO[str], IO[str], IO[str]]:
    """(cluster_r, cluster_w, scheduler_r, scheduler_w) over a local
    socketpair — the two ends of the 'network'."""
    a, b = socket.socketpair()
    return (
        a.makefile("r", encoding="utf-8"),
        a.makefile("w", encoding="utf-8"),
        b.makefile("r", encoding="utf-8"),
        b.makefile("w", encoding="utf-8"),
    )


class _CellLease:
    """One cell's resourcelock: holder + TTL + its monotone fencing
    epoch.  The default cell "" is the classic single-fleet lease —
    every pre-cell code path reads/writes it through the back-compat
    properties below."""

    __slots__ = ("holder", "expires", "epoch", "holders")

    def __init__(self) -> None:
        self.holder: str | None = None
        self.expires: float = 0.0
        self.epoch: int = 0
        self.holders: dict[int, str] = {}  # audit: epoch → holder


class ExternalCluster:
    def __init__(
        self,
        reader: IO[str] | None = None,
        writer: IO[str] | None = None,
        history: int = 1000,
    ) -> None:
        self._lock = threading.RLock()
        self._sessions: list[tuple[IO[str], IO[str]]] = []
        # -- watch-resume bookkeeping (≙ apiserver resourceVersions +
        # the bounded watch cache a reflector resumes from): every
        # broadcast event gets a monotonically increasing RV and lands
        # in a bounded history ring; a reconnecting session asks for
        # everything after its last-seen RV ("watchResume") and gets
        # either the missed tail or a 410-style "gone" forcing a
        # full re-list.
        self._rv = 0
        self._history: "collections.deque[dict]" = collections.deque(
            maxlen=history
        )
        self.pods: dict[str, Pod] = {}
        # (namespace, name) → uid index for the k8s dialect's
        # path-addressed writes; pods are never removed (evict returns
        # them to Pending), so submit() is the only maintenance site.
        self._pods_by_name: dict[tuple[str, str], str] = {}
        self.nodes: dict[str, Node] = {}
        self.groups: dict[str, PodGroup] = {}
        self.queues: dict[str, Queue] = {}
        self.binds: list[tuple[str, str]] = []
        self.evictions: list[tuple[str, str]] = []
        self.status_updates: list[PodGroup] = []
        # k8s-dialect write log: every apiserver-shaped request as it
        # arrived on the wire — (verb, path, object) — so tests can
        # assert the exact shapes a real apiserver would receive.
        self.k8s_writes: list[tuple[str, str, dict]] = []
        self.k8s_events: list[dict] = []  # core/v1 Event objects POSTed
        self.fail_bind_pods: set[str] = set()  # inject failures by pod name
        self._threads: list[threading.Thread] = []
        self._started = False
        # -- the resourcelocks (≙ resourcelock.LeaseLock on the
        # apiserver), PER CELL (doc/design/multi-cell.md): each cell
        # mints its own monotone fencing-epoch sequence, so N fenced
        # schedulers lead N disjoint partitions of the fleet
        # concurrently.  The default cell "" is the classic
        # single-fleet lease; the back-compat properties below keep
        # every pre-cell caller working unchanged.
        self._cell_leases: dict[str, _CellLease] = {"": _CellLease()}
        self.stale_epoch_rejections = 0
        # -- cell scoping (doc/design/multi-cell.md) -------------------
        # Data-plane writes carrying a `cell` are rejected BEFORE any
        # state is touched when their target (bind: the node; evict /
        # status: the pod / group via its queue) lies in a DIFFERENT
        # cell — a cell-A scheduler can never mutate cell-B state.
        self.cross_cell_rejections = 0
        #: The cell of the request CURRENTLY dispatching (stashed under
        #: the cluster lock around _handle, like ChaosCluster's epoch
        #: stash) — _bind_pod/_evict_pod enforce scope from it for
        #: BOTH wire dialects.
        self._req_cell: str | None = None
        #: The W3C traceparent of the request CURRENTLY dispatching
        #: (cross-scheduler trace stitching, doc/design/
        #: observability.md): stashed like the cell, consumed by the
        #: reclaim verbs (a claim REMEMBERS its claimant's context so
        #: the donor stitches its drain under the same trace id) and
        #: by the cluster's own handler spans.  Never logged into the
        #: hashed wire log — stitching is decision-invisible.
        self._req_trace: str | None = None
        #: writer-id → cell, learned from each session's requests: the
        #: partition fault family needs to know which sessions belong
        #: to a dark cell (broadcast suppression keys on this).
        self._session_cells: dict[int, str] = {}
        # -- cross-cell reclaim (offerCapacity / claimCapacity) --------
        # A starved cell REQUESTS capacity; the donor cell's own
        # scheduler evicts via its normal drain machinery and OFFERS a
        # freed node; the cluster re-cells it atomically.  A claim the
        # donor never answers (partition!) times out and ROLLS BACK —
        # no node is ever left in limbo.  The clock is supplied by the
        # driver (chaos: the tick counter) via `claim_clock` +
        # `expire_reclaims`.
        self.reclaim_claims: dict[int, dict] = {}
        self._claim_seq = 0
        self.claim_clock = 0
        self.reclaim_granted = 0
        self.reclaim_rolled_back = 0
        # Multi-node claims partially filled at TTL close "granted"
        # with fractional=True (the filled nodes stay moved; the
        # unfilled remainder rolls back to nothing) — counted apart.
        self.reclaim_expired = 0
        # The leaders' mirrored operational-state snapshots (statestore
        # HA adoption), PER CELL: last-write-wins within a cell,
        # epoch-fenced on write like every data-plane verb, readable
        # by any contender OF THAT CELL — takeover adoption stays
        # cell-local.  The k8s dialect lands here too (ConfigMap-
        # shaped write).  Key "" is the classic uncelled snapshot.
        self.state_snapshots: dict[str, dict | None] = {}
        # The leader's mirrored AOT compile artifacts
        # (doc/design/compile-artifacts.md): entry-name → payload,
        # merged per put (a bank holds MANY programs, unlike the
        # single statestore snapshot), bounded FIFO so a pathological
        # shape churn cannot grow the control plane unboundedly.
        # Epoch-fenced on write, readable by any contender; the k8s
        # dialect lands here too (ConfigMap-shaped write).
        self.compile_artifacts: dict[str, dict] = {}
        if reader is not None and writer is not None:
            self.attach(reader, writer)

    # -- per-cell lease access + back-compat single-lease surface -------
    def lease(self, cell: str = "") -> _CellLease:
        lease = self._cell_leases.get(cell)
        if lease is None:
            lease = self._cell_leases[cell] = _CellLease()
        return lease

    @property
    def lease_holder(self) -> str | None:
        return self.lease("").holder

    @lease_holder.setter
    def lease_holder(self, v: str | None) -> None:
        self.lease("").holder = v

    @property
    def lease_expires(self) -> float:
        return self.lease("").expires

    @lease_expires.setter
    def lease_expires(self, v: float) -> None:
        self.lease("").expires = v

    @property
    def lease_epoch(self) -> int:
        return self.lease("").epoch

    @lease_epoch.setter
    def lease_epoch(self, v: int) -> None:
        self.lease("").epoch = v

    @property
    def epoch_holders(self) -> dict[int, str]:
        return self.lease("").holders

    @property
    def state_snapshot(self) -> dict | None:
        return self.state_snapshots.get("")

    @state_snapshot.setter
    def state_snapshot(self, v: dict | None) -> None:
        self.state_snapshots[""] = v

    # -- cell resolution (doc/design/multi-cell.md) ---------------------
    def cell_of_node(self, name: str) -> str:
        """A node's cell assignment ("" = shared / uncelled)."""
        from kube_batch_tpu.client.adapter import CELL_LABEL

        node = self.nodes.get(name)
        return str(node.labels.get(CELL_LABEL, "")) if node else ""

    def cell_of_pod(self, pod: Pod) -> str:
        """A pod's cell: its group's queue's cell, with the pod label
        as the groupless fallback ("" = shared)."""
        from kube_batch_tpu.client.adapter import CELL_LABEL

        if pod.group:
            group = self.groups.get(pod.group)
            if group is not None:
                queue = self.queues.get(group.queue)
                cell = getattr(queue, "cell", "") if queue else ""
                if cell:
                    return str(cell)
        return str(pod.labels.get(CELL_LABEL, ""))

    def _cell_scope_violation(self, pod: Pod | None,
                              node_name: str | None) -> str | None:
        """The authoritative cell-scope check, shared by both wire
        dialects: a write from a cell-declaring session may only touch
        objects of ITS cell (or shared ones).  Returns the rejection
        message, or None when the write may proceed.  Uncelled
        writers (no `cell` on the request) pass — single-fleet
        deploys are unchanged."""
        cell = self._req_cell
        if not cell:
            return None
        if node_name is not None and node_name in self.nodes:
            node_cell = self.cell_of_node(node_name)
            if node_cell and node_cell != cell:
                return (
                    f"cell-scope: node {node_name!r} belongs to cell "
                    f"{node_cell!r}, writer is fenced to {cell!r}"
                )
        if pod is not None:
            pod_cell = self.cell_of_pod(pod)
            if pod_cell and pod_cell != cell:
                return (
                    f"cell-scope: pod {pod.uid!r} belongs to cell "
                    f"{pod_cell!r}, writer is fenced to {cell!r}"
                )
        return None

    def _reject_cell_scope(self, writer, rid: int, why: str) -> None:
        self.cross_cell_rejections += 1
        self._on_cell_reject(why)
        self._respond(writer, rid, False, why, code="CellScope")

    # -- sessions -------------------------------------------------------
    def attach(self, reader: IO[str], writer: IO[str]) -> None:
        """Register one scheduler session (reader serves its write
        requests once start()ed; writer receives broadcast events)."""
        with self._lock:
            self._sessions.append((reader, writer))
            if self._started:  # already serving: start this one too
                t = threading.Thread(
                    target=self._serve, args=(reader,), daemon=True
                )
                self._threads.append(t)
                t.start()

    def replay(self, writer: IO[str]) -> None:
        """LIST replay for a late-attaching session: every current
        object as ADDED, then SYNC carrying the collection's
        resourceVersion (≙ informer re-list + HasSynced; the reflector
        resumes its watch from the LIST's RV)."""
        with self._lock:
            for q in self.queues.values():
                self._emit_to(writer, "ADDED", "Queue", encode_queue(q))
            for n in self.nodes.values():
                self._emit_to(writer, "ADDED", "Node", encode_node(n))
            for g in self.groups.values():
                self._emit_to(writer, "ADDED", "PodGroup", encode_pod_group(g))
            for p in self.pods.values():
                self._emit_to(writer, "ADDED", "Pod", encode_pod(p))
            self._emit_to(writer, None, None, None, raw={
                "type": "SYNC", "resourceVersion": self._rv,
            })

    # -- wire out -------------------------------------------------------
    def _emit_to(self, writer, mtype, kind, obj, raw: dict | None = None):
        msg = raw if raw is not None else {
            "type": mtype, "kind": kind, "object": obj,
        }
        try:
            writer.write(json.dumps(msg) + "\n")
            writer.flush()
        except (OSError, ValueError):
            pass  # dead session; its reader thread is ending too

    def _session_blocked(self, writer) -> bool:
        """Broadcast suppression hook: True = this session receives no
        watch events right now (a fully partitioned cell — see
        chaos/cells.py).  The event still lands in the history ring,
        so the healed session resumes the missed tail."""
        del writer
        return False

    def _emit(self, mtype: str, kind: str, obj: dict) -> None:
        with self._lock:
            self._rv += 1
            msg = {
                "type": mtype, "kind": kind, "object": obj,
                "resourceVersion": self._rv,
            }
            self._history.append(msg)
            for _r, w in self._sessions:
                if self._session_blocked(w):
                    continue
                self._emit_to(w, None, None, None, raw=msg)

    def _respond(
        self, writer: IO[str], rid: int, ok: bool, error: str = "",
        code: str | None = None, extra: dict | None = None,
    ) -> None:
        msg: dict = {"type": "RESPONSE", "id": rid, "ok": ok}
        if error:
            msg["error"] = error
        if code:
            # Structured error class (today: "StaleEpoch") so clients
            # classify without parsing the human-readable message.
            msg["code"] = code
        if extra:
            msg.update(extra)
        with self._lock:
            self._emit_to(writer, None, None, None, raw=msg)

    def sync(self) -> None:
        """Mark the initial LIST replay complete (≙ informer HasSynced)."""
        with self._lock:
            for _r, w in self._sessions:
                self._emit_to(w, None, None, None, raw={
                    "type": "SYNC", "resourceVersion": self._rv,
                })

    # -- authoritative world mutations (all emit watch events) ----------
    def add_node(self, node: Node) -> None:
        with self._lock:
            self.nodes[node.name] = node
            self._emit("ADDED", "Node", encode_node(node))

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self.nodes.pop(name, None)
            if node is None:
                return
            # Pods on the dead node go Pending again (controller restart).
            for pod in self.pods.values():
                if pod.node == name:
                    pod.node = None
                    pod.status = TaskStatus.PENDING
                    self._emit("MODIFIED", "Pod", encode_pod(pod))
            self._emit("DELETED", "Node", encode_node(node))

    def add_queue(self, queue: Queue) -> None:
        with self._lock:
            self.queues[queue.name] = queue
            self._emit("ADDED", "Queue", encode_queue(queue))

    def submit(self, group: PodGroup, pods: list[Pod]) -> None:
        with self._lock:
            self.groups[group.name] = group
            self._emit("ADDED", "PodGroup", encode_pod_group(group))
            for pod in pods:
                pod.group = group.name
                self.pods[pod.uid] = pod
                key = (pod.namespace, pod.name)
                # First submission wins, matching the linear scan this
                # index replaced (dict iteration = insertion order).
                self._pods_by_name.setdefault(key, pod.uid)
                self._emit("ADDED", "Pod", encode_pod(pod))

    def tick(self) -> None:
        """Bound → Running (kubelet starting containers)."""
        with self._lock:
            for pod in self.pods.values():
                if pod.status == TaskStatus.BOUND:
                    pod.status = TaskStatus.RUNNING
                    self._emit("MODIFIED", "Pod", encode_pod(pod))

    def delete_pod(self, uid: str) -> None:
        """Remove a pod for good (a controller garbage-collecting a
        finished workload — unlike evict, nothing recreates it)."""
        with self._lock:
            pod = self.pods.pop(uid, None)
            if pod is None:
                return
            key = (pod.namespace, pod.name)
            if self._pods_by_name.get(key) == uid:
                self._pods_by_name.pop(key, None)
            self._emit("DELETED", "Pod",
                       {"uid": pod.uid, "name": pod.name})

    def complete_group(self, name: str) -> None:
        """A whole job finishes: its pods and PodGroup are deleted
        (the controller reaping a Succeeded workload) — the watch
        stream carries the teardown like any other churn."""
        with self._lock:
            group = self.groups.pop(name, None)
            for uid in [u for u, p in self.pods.items() if p.group == name]:
                self.delete_pod(uid)
            if group is not None:
                self._emit("DELETED", "PodGroup", encode_pod_group(group))

    def expire_history(self) -> None:
        """Drop the watch-event history ring (≙ apiserver etcd
        compaction): the next `watchResume` over any missed tail is
        forced onto the 410-Gone path and the client must re-list."""
        with self._lock:
            self._history.clear()

    # -- the serve loop (scheduler write requests) ----------------------
    def start(self) -> "ExternalCluster":
        with self._lock:
            self._started = True
            for reader, _w in self._sessions:
                t = threading.Thread(
                    target=self._serve, args=(reader,), daemon=True
                )
                self._threads.append(t)
                t.start()
        return self

    def _writer_for(self, reader: IO[str]) -> IO[str] | None:
        with self._lock:
            for r, w in self._sessions:
                if r is reader:
                    return w
        return None

    def _serve(self, reader: IO[str]) -> None:
        writer = self._writer_for(reader)
        try:
            for line in reader:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue  # one garbled request must not kill serving
                if msg.get("type") != "REQUEST":
                    continue
                self._handle(writer, msg)
        except (OSError, ValueError):
            # ValueError = iterating a concurrently-closed file object;
            # JSONDecodeError never reaches here (handled per line).
            pass  # scheduler hung up
        finally:
            # Prune the dead session: repeated failovers must not leave
            # broadcasts writing to an ever-growing list of corpses.
            # Its cell tag goes too — id() values get recycled, and a
            # stale entry could mis-tag (and partition-suppress) a
            # future session whose writer lands on the same address.
            with self._lock:
                for r, w in self._sessions:
                    if r is reader:
                        self._session_cells.pop(id(w), None)
                self._sessions = [
                    (r, w) for r, w in self._sessions if r is not reader
                ]

    # -- lease arbitration (≙ resourcelock acquire-or-renew) ------------
    def _handle_lease(self, writer, verb: str, msg: dict) -> None:
        """One cell's resourcelock dance.  The request's `cell`
        selects WHICH lease ("" = the classic single-fleet one): each
        cell mints its own monotone epoch sequence, so two cells'
        leaderships never fence each other."""
        rid, holder = msg["id"], msg.get("holder", "")
        cell = str(msg.get("cell") or "")
        lease = self.lease(cell)
        now = time.monotonic()
        if verb == "releaseLease":
            if lease.holder == holder:
                lease.holder = None
                lease.expires = 0.0
                # The epoch is NOT reset: monotonicity is the fencing
                # guarantee, and the next acquire mints a fresh one.
            self._respond(writer, rid, True)
            return
        ttl = float(msg.get("ttl", 15.0))
        expired = now >= lease.expires
        if verb == "renewLease" and lease.holder != holder:
            # A renewal after the lease was TAKEN must fail: the old
            # holder has to stand down (≙ RunOrDie's OnStoppedLeading).
            # A merely-expired-but-unclaimed lease renews fine — the
            # holder was just slow, and nobody else is leading.
            self._respond(
                writer, rid, False,
                f"lease lost (held by {lease.holder!r})",
            )
            return
        if verb == "acquireLease" and not expired and lease.holder not in (
            None, holder
        ):
            self._respond(
                writer, rid, False,
                f"lease held by {lease.holder!r} for "
                f"{lease.expires - now:.1f}s",
            )
            return
        if verb == "acquireLease" and (
            lease.holder != holder or expired or lease.epoch == 0
        ):
            # A change of hands (or reviving an expired lease — even by
            # its previous holder: its pre-expiry in-flight writes are
            # no longer trustworthy) mints the next epoch.  An
            # idempotent re-acquire by the live current holder keeps
            # its epoch.
            lease.epoch += 1
            lease.holders[lease.epoch] = holder
            self._on_epoch_advance(lease.epoch, holder, cell)
        lease.holder = holder
        lease.expires = now + ttl
        self._respond(writer, rid, True,
                      extra={"epoch": lease.epoch})

    def expire_lease(self, cell: str = "") -> None:
        """Force a cell's lease to expire NOW (≙ the holder's
        renewals stopping and the TTL running out — a leader crash as
        the cluster observes it): the next acquire by anyone succeeds
        and mints a higher epoch.  The holder field is left as the
        corpse's identity, exactly like a real resourcelock."""
        with self._lock:
            self.lease(cell).expires = 0.0

    # Hooks a subclass (chaos/faults.ChaosCluster) can instrument.
    def _on_epoch_advance(self, epoch: int, holder: str,
                          cell: str = "") -> None:
        pass

    def _on_stale_reject(self, msg: dict) -> None:
        pass

    def _on_cell_reject(self, why: str) -> None:
        pass

    def _on_reclaim(self, entry: dict) -> None:
        pass

    @property
    def FENCED_VERBS(self):  # noqa: N802 — constant-shaped
        """Data-plane verbs subject to epoch fencing — the ONE
        canonical set, shared with the client's local fence
        (client/adapter.py · FENCED_VERBS; lazy import: adapter
        imports the cache at load time).  Watch/lease/list verbs and
        the breaker's `ping` probe are NOT fenced: a standby must
        keep ingesting and probing, and the elector itself is how a
        deposed leader gets a NEW epoch."""
        from kube_batch_tpu.client.adapter import FENCED_VERBS

        return FENCED_VERBS

    def _check_epoch(self, writer, msg: dict) -> bool:
        """True when the request may proceed.  A data-plane write
        stamped with a non-current epoch is a zombie — rejected with
        the structured StaleEpoch code (no retry: the caller's
        leadership is gone, not its wire).  The epoch is checked
        against the WRITER'S CELL's lease: each cell fences its own
        epoch sequence."""
        epoch = msg.get("epoch")
        if epoch is None:
            return True  # unfenced caller (no leader election wired)
        verb = msg.get("verb")
        if "path" not in msg and verb not in self.FENCED_VERBS:
            return True
        lease = self.lease(str(msg.get("cell") or ""))
        if int(epoch) == lease.epoch:
            return True
        self.stale_epoch_rejections += 1
        self._on_stale_reject(msg)
        self._respond(
            writer, msg["id"], False,
            f"stale epoch {epoch} (current epoch "
            f"{lease.epoch}, holder {lease.holder!r})",
            code="StaleEpoch",
        )
        return False

    # -- apiserver-dialect writes (client/k8s_write.py shapes) ----------
    def _find_pod(self, namespace: str, name: str) -> Pod | None:
        """O(1) by-name lookup for the k8s dialect's path-addressed
        writes: a 47.5k-pod gang commit issues one of these per
        Binding POST, and a linear scan under the global lock would
        make the fixture consumer quadratic in cluster size — the
        bottleneck the scheduler's bind fan-out exists to remove."""
        key = (namespace, name)
        uid = self._pods_by_name.get(key)
        pod = self.pods.get(uid) if uid is not None else None
        if pod is not None:
            return pod
        # Index miss: tests (and uid churn — a controller recreating a
        # same-named pod) mutate self.pods directly, so fall back to
        # the scan the index replaced and repair the entry.
        for pod in self.pods.values():
            if pod.namespace == namespace and pod.name == name:
                self._pods_by_name[key] = pod.uid
                return pod
        return None

    def _bind_pod(self, writer, rid: int, pod: Pod | None,
                  node_name: str) -> None:
        """Shared bind semantics for both wire dialects.  Cell scope
        is enforced HERE, cluster-side, before any state is touched:
        a cell-A scheduler can never bind onto a cell-B node (or bind
        a cell-B pod), whatever its epoch says."""
        scope_err = (
            self._cell_scope_violation(pod, node_name)
            if pod is not None else None
        )
        if scope_err is not None:
            self._reject_cell_scope(writer, rid, scope_err)
        elif pod is None:
            self._respond(writer, rid, False, "pod not found")
        elif pod.name in self.fail_bind_pods:
            self._respond(writer, rid, False, "injected bind failure")
        elif node_name not in self.nodes:
            self._respond(writer, rid, False, f"node {node_name} not found")
        else:
            pod.node = node_name
            pod.status = TaskStatus.BOUND
            self.binds.append((pod.name, node_name))
            self._respond(writer, rid, True)
            self._emit("MODIFIED", "Pod", encode_pod(pod))

    def _evict_pod(self, writer, rid: int, pod: Pod | None,
                   reason: str) -> None:
        scope_err = (
            self._cell_scope_violation(pod, None)
            if pod is not None else None
        )
        if scope_err is not None:
            self._reject_cell_scope(writer, rid, scope_err)
        elif pod is None:
            self._respond(writer, rid, False, "pod not found")
        else:
            pod.node = None
            pod.status = TaskStatus.PENDING
            self.evictions.append((pod.name, reason))
            self._respond(writer, rid, True)
            self._emit("MODIFIED", "Pod", encode_pod(pod))

    def _handle_k8s(self, writer, msg: dict) -> None:
        """Route an apiserver-shaped request (verb + resource path +
        k8s body) the way a real apiserver would, validating the shapes
        the reference's REST calls carry."""
        import re

        verb, rid = msg.get("verb"), msg["id"]
        path, obj = msg.get("path", ""), msg.get("object") or {}
        self.k8s_writes.append((verb, path, obj))

        m = re.fullmatch(r"/api/v1/namespaces/([^/]+)/pods/([^/]+)/binding",
                         path)
        if m and verb == "create":
            if obj.get("kind") != "Binding" or \
                    obj.get("target", {}).get("kind") != "Node":
                self._respond(writer, rid, False,
                              "malformed Binding object")
                return
            if obj.get("metadata", {}).get("name") != m.group(2):
                self._respond(writer, rid, False,
                              "Binding name does not match path")
                return
            self._bind_pod(writer, rid, self._find_pod(*m.groups()),
                           obj["target"].get("name", ""))
            return

        m = re.fullmatch(r"/api/v1/namespaces/([^/]+)/pods/([^/]+)", path)
        if m and verb == "delete":
            pod = self._find_pod(*m.groups())
            want_uid = (obj.get("preconditions") or {}).get("uid")
            if pod is not None and want_uid and pod.uid != want_uid:
                # ≙ apiserver 409: the named pod is not the one the
                # eviction decision was made against.
                self._respond(writer, rid, False,
                              "precondition failed: uid mismatch")
                return
            self._evict_pod(writer, rid, pod, "k8s-delete")
            return

        m = re.fullmatch(
            r"/apis/[^/]+/v1alpha\d/namespaces/([^/]+)/"
            r"podgroups/([^/]+)/status", path,
        )
        if m and verb == "update":
            if obj.get("kind") != "PodGroup" or "status" not in obj:
                self._respond(writer, rid, False,
                              "malformed PodGroup status object")
                return
            name, status = m.group(2), obj["status"]
            group = self.groups.get(name)
            if group is not None:
                from kube_batch_tpu.api.types import (
                    PodGroupCondition,
                    PodGroupPhase,
                )

                group.phase = PodGroupPhase(status.get("phase", "Pending"))
                group.running = int(status.get("running", 0))
                group.succeeded = int(status.get("succeeded", 0))
                group.failed = int(status.get("failed", 0))
                group.conditions = [
                    PodGroupCondition(
                        type=c.get("type", "Note"),
                        status=c.get("status") == "True",
                        reason=c.get("reason", ""),
                        message=c.get("message", ""),
                    )
                    for c in status.get("conditions", [])
                ]
                self.status_updates.append(group)
            self._respond(writer, rid, group is not None,
                          "" if group is not None else "podgroup not found")
            return

        m = re.fullmatch(r"/api/v1/nodes/([^/]+)", path)
        if m and verb in ("patch", "update"):
            # ≙ kubectl cordon/uncordon: spec.unschedulable PATCH from
            # the health ledger's cordon sink.  The cluster mutates the
            # node and broadcasts MODIFIED, so every attached session
            # (and the writer itself, symmetrically) observes the
            # cordon on the watch stream.
            node = self.nodes.get(m.group(1))
            if node is None:
                self._respond(writer, rid, False,
                              f"node {m.group(1)} not found")
                return
            spec = obj.get("spec") or {}
            if "unschedulable" not in spec:
                self._respond(writer, rid, False,
                              "patch carries no spec.unschedulable")
                return
            node.unschedulable = bool(spec["unschedulable"])
            self._respond(writer, rid, True)
            self._emit("MODIFIED", "Node", encode_node(node))
            return

        m = re.fullmatch(
            r"/api/v1/namespaces/([^/]+)/configmaps/([^/]+)", path
        )
        if m and verb in ("create", "update", "patch"):
            from kube_batch_tpu.client.k8s_write import (
                COMPILE_CONFIGMAP_NAME,
                COMPILE_CONFIGMAP_NAMESPACE,
                STATE_CONFIGMAP_NAME,
                STATE_CONFIGMAP_NAMESPACE,
            )

            if m.groups() == (COMPILE_CONFIGMAP_NAMESPACE,
                              COMPILE_CONFIGMAP_NAME):
                # The artifact bank's mirror in apiserver dialect: a
                # ConfigMap whose data maps entry-name → one JSON
                # entry payload (epoch-fenced by path above).  Each
                # write MERGES its keys — the bank holds many
                # programs, and a patch must not clobber its siblings.
                from kube_batch_tpu.compile_cache import (
                    payloads_from_configmap_data,
                )

                data = obj.get("data")
                if obj.get("kind") != "ConfigMap" or \
                        not isinstance(data, dict):
                    self._respond(writer, rid, False,
                                  "malformed compile-artifacts "
                                  "ConfigMap")
                    return
                for payload in payloads_from_configmap_data(data):
                    self._merge_compile_artifact(payload)
                self._respond(writer, rid, True)
                return
            if m.groups() != (STATE_CONFIGMAP_NAMESPACE,
                              STATE_CONFIGMAP_NAME):
                # Only the dedicated control-plane objects route here —
                # an unrelated ConfigMap write must not clobber the
                # snapshot a successor will adopt.
                self._respond(writer, rid, False,
                              f"unhandled k8s request {verb} {path}")
                return
            # The statestore's HA mirror in apiserver dialect: a
            # ConfigMap whose data.state carries the compacted
            # operational snapshot (epoch-fenced by path above).
            import json as _json

            raw = (obj.get("data") or {}).get("state")
            if obj.get("kind") != "ConfigMap" or not isinstance(raw, str):
                self._respond(writer, rid, False,
                              "malformed state ConfigMap")
                return
            try:
                payload = _json.loads(raw)
            except _json.JSONDecodeError:
                self._respond(writer, rid, False,
                              "state ConfigMap data.state is not JSON")
                return
            self.state_snapshots[self._req_cell or ""] = (
                payload if isinstance(payload, dict) else None
            )
            self._respond(writer, rid, True)
            return

        m = re.fullmatch(r"/api/v1/namespaces/([^/]+)/events", path)
        if m and verb == "create":
            if obj.get("kind") != "Event" or "involvedObject" not in obj:
                self._respond(writer, rid, False, "malformed Event object")
                return
            self.k8s_events.append(obj)
            self._respond(writer, rid, True)
            return

        self._respond(writer, rid, False,
                      f"unhandled k8s request {verb} {path}")

    #: Mirror bound: oldest entries drop past this — a pathological
    #: shape churn must not grow the control-plane object unboundedly
    #: (the local bank on disk is the full record).
    COMPILE_ARTIFACTS_MAX = 32

    def _merge_compile_artifact(self, payload: dict) -> None:
        """Merge one mirrored bank entry (keyed by its entry name;
        re-puts of the same key replace in place), bounded FIFO."""
        name = str(payload.get("name") or f"anon-{len(self.compile_artifacts)}")
        self.compile_artifacts.pop(name, None)
        self.compile_artifacts[name] = payload
        while len(self.compile_artifacts) > self.COMPILE_ARTIFACTS_MAX:
            self.compile_artifacts.pop(
                next(iter(self.compile_artifacts))
            )

    # -- watch resume (≙ reflector re-watch from last RV / 410 Gone) ----
    def _handle_watch_resume(self, writer, rid: int, since: int) -> None:
        """Serve the missed event tail when the history ring still
        covers `since`; otherwise answer the 410-Gone analog and the
        client must re-list.  Either way a SYNC trails the replay so
        the session's adapter re-arms its sync gate."""
        if since > self._rv:
            # The client is AHEAD of us: this cluster incarnation was
            # restarted (fresh RV space) — its history cannot mean what
            # the client thinks.  Force the re-list, like an apiserver
            # answering 410 for an unknown RV.
            self._respond(
                writer, rid, False,
                f"410 gone: rv {since} is from another watch incarnation",
            )
            return
        if since < self._rv and (
            not self._history or self._history[0]["resourceVersion"] > since + 1
        ):
            # The tail the client missed has partly fallen out of the
            # ring — replaying the remainder would silently skip events.
            self._respond(
                writer, rid, False,
                f"410 gone: watch history starts after rv {since}",
            )
            return
        self._respond(writer, rid, True)
        for past in self._history:
            if past["resourceVersion"] > since:
                self._emit_to(writer, None, None, None, raw=past)
        self._emit_to(writer, None, None, None, raw={
            "type": "SYNC", "resourceVersion": self._rv,
        })

    def _handle(self, writer: IO[str], msg: dict) -> None:
        verb, rid = msg.get("verb"), msg["id"]
        with self._lock:
            cell = msg.get("cell")
            if cell is not None:
                # Tag the session (the partition fault family keys
                # broadcast suppression on it) and stash the request
                # cell for the dialect-shared scope checks.
                self._session_cells[id(writer)] = str(cell)
            self._req_cell = str(cell) if cell is not None else None
            tp = msg.get("traceparent")
            self._req_trace = str(tp) if isinstance(tp, str) else None
            try:
                self._handle_locked(writer, verb, rid, msg)
            finally:
                self._req_cell = None
                self._req_trace = None

    def _handle_locked(self, writer: IO[str], verb, rid,
                       msg: dict) -> None:
        if self._req_trace is None:
            return self._dispatch_locked(writer, verb, rid, msg)
        # Trace stitching, receiving side: the cluster's handling of a
        # context-carrying request records as a CHILD span under the
        # propagated traceparent (no-op when tracing is off) — the
        # cluster hop shows up in the same Perfetto tree as the
        # scheduler that issued the write.
        from kube_batch_tpu import trace

        with trace.adopted_span(
            "cluster:" + str(verb or msg.get("path") or "?"),
            self._req_trace,
        ):
            return self._dispatch_locked(writer, verb, rid, msg)

    def _dispatch_locked(self, writer: IO[str], verb, rid,
                         msg: dict) -> None:
        if not self._check_epoch(writer, msg):
            return  # zombie write from a deposed epoch: rejected
        if "path" in msg:  # apiserver-dialect write
            self._handle_k8s(writer, msg)
        elif verb == "watchResume":
            self._handle_watch_resume(writer, rid,
                                      int(msg.get("since", 0)))
        elif verb == "list":
            self._respond(writer, rid, True)
            self.replay(writer)
        elif verb in ("acquireLease", "renewLease", "releaseLease"):
            self._handle_lease(writer, verb, msg)
        elif verb == "bind":
            self._bind_pod(
                writer, rid, self.pods.get(msg["pod"]), msg["node"]
            )
        elif verb == "evict":
            self._evict_pod(
                writer, rid, self.pods.get(msg["pod"]),
                msg.get("reason", ""),
            )
        elif verb == "ping":
            # Health probe (the wire breaker's half-open check):
            # answer, touch nothing.
            self._respond(writer, rid, True)
        elif verb == "putStateSnapshot":
            # The statestore's HA mirror (epoch-fenced above):
            # last-write-wins PER CELL, no watch event —
            # control-plane metadata, not cluster state.  A cell's
            # takeover successor adopts ITS cell's snapshot only.
            obj = msg.get("object")
            self.state_snapshots[self._req_cell or ""] = (
                obj if isinstance(obj, dict) else None
            )
            self._respond(writer, rid, True)
        elif verb == "getStateSnapshot":
            self._respond(writer, rid, True, extra={
                "object": self.state_snapshots.get(
                    self._req_cell or ""
                ),
            })
        elif verb == "claimCapacity":
            self._handle_claim(writer, rid, msg)
        elif verb == "offerCapacity":
            self._handle_offer(writer, rid, msg)
        elif verb == "listClaims":
            # Unfenced read: the donor cell's scheduler polls for
            # claims targeting it (adoption-time reads never need
            # leadership).  role="claimant" flips the filter: the
            # CLAIMANT polls its own claims — any state, so it can
            # observe grant/rollback/fractional-expire resolutions.
            # The default (donor view, pending only) is unchanged: a
            # donor must never see its own outbound claims here, or
            # it would drain victims against itself.
            cell = str(msg.get("cell") or "")
            if msg.get("role") == "claimant":
                claims = [
                    dict(c) for _cid, c in sorted(
                        self.reclaim_claims.items()
                    )
                    if c["to"] == cell
                ]
            else:
                claims = [
                    dict(c) for _cid, c in sorted(
                        self.reclaim_claims.items()
                    )
                    if c["from"] == cell and c["state"] == "pending"
                ]
            self._respond(writer, rid, True,
                          extra={"object": claims})
        elif verb == "putCompileArtifact":
            # The AOT artifact bank's cluster-side mirror
            # (epoch-fenced above): one entry merged per put, no
            # watch event — control-plane metadata like the state
            # snapshot, but a SET (a bank holds many programs).
            obj = msg.get("object")
            if not isinstance(obj, dict):
                self._respond(writer, rid, False,
                              "malformed compile artifact")
            else:
                self._merge_compile_artifact(obj)
                self._respond(writer, rid, True)
        elif verb == "getCompileArtifact":
            self._respond(writer, rid, True, extra={
                "object": list(self.compile_artifacts.values()),
            })
        elif verb == "updatePodGroup":
            from kube_batch_tpu.client.codec import decode_pod_group

            group = decode_pod_group(msg["object"])
            if self._req_cell:
                queue = self.queues.get(group.queue)
                gcell = getattr(queue, "cell", "") if queue else ""
                if gcell and gcell != self._req_cell:
                    self._reject_cell_scope(
                        writer, rid,
                        f"cell-scope: group {group.name!r} belongs "
                        f"to cell {gcell!r}, writer is fenced to "
                        f"{self._req_cell!r}",
                    )
                    return
            if group.name in self.groups:
                self.groups[group.name] = group
            self.status_updates.append(group)
            self._respond(writer, rid, True)
        else:
            self._respond(writer, rid, False, f"unknown verb {verb}")

    # -- cross-cell reclaim (doc/design/multi-cell.md) ------------------
    #: Default claim TTL in claim-clock units (chaos: ticks) when the
    #: claimant names none.
    RECLAIM_TTL_DEFAULT = 8

    def _handle_claim(self, writer, rid: int, msg: dict) -> None:
        """A starved cell REQUESTS capacity from a donor cell.  The
        cluster records the pending claim; the donor's own scheduler
        discovers it (listClaims), frees a node through its normal
        drain machinery, and offers it back.  Nothing moves yet —
        creation is bookkeeping only, so a claim that dies with a
        partition rolls back to exactly nothing."""
        to_cell = str(msg.get("cell") or "")
        donor = str(msg.get("from") or "")
        if not to_cell or not donor or donor == to_cell:
            self._respond(
                writer, rid, False,
                f"malformed capacity claim (cell={to_cell!r} "
                f"from={donor!r})",
            )
            return
        ttl = int(msg.get("ttlTicks", self.RECLAIM_TTL_DEFAULT))
        nodes = max(int(msg.get("nodes", 1)), 1)
        self._claim_seq += 1
        claim = {
            "id": self._claim_seq,
            "to": to_cell,
            "from": donor,
            "state": "pending",
            "created": self.claim_clock,
            "deadline": self.claim_clock + max(ttl, 1),
            "node": None,
            # Multi-node claims: the donor fills the claim one offer
            # at a time; `granted` accumulates the moved nodes and
            # `node` keeps the first for single-node back-compat.
            "nodes": nodes,
            "granted": [],
            "resolved": None,
            # The claimant's propagated trace context: listClaims
            # hands it to the donor, whose drain + offer open child
            # spans under it — one Perfetto tree spanning both
            # schedulers.  Rides OUTSIDE the hashed wire-log entries
            # (which name only op/claim/cells), so stitching on/off
            # never moves the chaos hash.
            "traceparent": self._req_trace,
        }
        self.reclaim_claims[claim["id"]] = claim
        entry = {
            "op": "reclaim-claim", "claim": claim["id"],
            "to": to_cell, "from": donor,
            "deadline": claim["deadline"],
        }
        if nodes > 1:
            # Only stamped for multi-node claims: single-node wire
            # entries stay byte-identical to the pre-autopilot hash.
            entry["nodes"] = nodes
        self._on_reclaim(entry)
        self._respond(writer, rid, True, extra={"claim": claim["id"]})

    def _handle_offer(self, writer, rid: int, msg: dict) -> None:
        """The donor cell OFFERS a freed node against a pending claim.
        The transfer is atomic under the cluster lock: validate, then
        re-cell the node and mark the claim granted in one step — the
        watch broadcast makes the node vanish from the donor's filter
        and appear in the claimant's.  An offer for a rolled-back (or
        unknown) claim is refused outright: after a partition the
        donor's drain was wasted work, but no node leaks into limbo."""
        from kube_batch_tpu.client.adapter import CELL_LABEL
        from kube_batch_tpu.api.types import TaskStatus

        donor = str(msg.get("cell") or "")
        claim = self.reclaim_claims.get(int(msg.get("claim", 0)))
        node = self.nodes.get(str(msg.get("node") or ""))
        if claim is None or claim["state"] != "pending":
            self._respond(
                writer, rid, False,
                f"claim {msg.get('claim')!r} is not pending "
                f"(state {claim['state'] if claim else 'unknown'!r})",
            )
            return
        if claim["from"] != donor:
            self._respond(
                writer, rid, False,
                f"claim {claim['id']} names donor {claim['from']!r}, "
                f"not {donor!r}",
            )
            return
        if node is None:
            self._respond(writer, rid, False,
                          f"node {msg.get('node')!r} not found")
            return
        if self.cell_of_node(node.name) != donor:
            self._respond(
                writer, rid, False,
                f"node {node.name!r} is not in donor cell {donor!r}",
            )
            return
        residents = sorted(
            p.name for p in self.pods.values()
            if p.node == node.name and p.status in (
                TaskStatus.BOUND, TaskStatus.RUNNING,
            )
        )
        if residents:
            # The donor must drain FIRST (its own scheduler, its own
            # evictions) — re-celling a node under live residents
            # would strand them outside their scheduler's scope.
            self._respond(
                writer, rid, False,
                f"node {node.name!r} still has resident pod(s) "
                f"{residents[:4]} — drain before offering",
            )
            return
        node.labels = {**node.labels, CELL_LABEL: claim["to"]}
        granted = claim.setdefault("granted", [])
        granted.append(node.name)
        claim["node"] = granted[0]  # single-node back-compat
        if len(granted) >= int(claim.get("nodes", 1)):
            # Full fill: the claim closes granted.  A partial fill
            # stays pending — more offers may land before the TTL
            # closes it fractionally (expire_reclaims).
            claim["state"] = "granted"
            claim["resolved"] = self.claim_clock
            self.reclaim_granted += 1
        self._on_reclaim({
            "op": "reclaim-grant", "claim": claim["id"],
            "node": node.name, "to": claim["to"], "from": donor,
        })
        self._respond(writer, rid, True)
        self._emit("MODIFIED", "Node", encode_node(node))

    def expire_reclaims(self) -> int:
        """Roll back every pending claim past its deadline (driver-
        clocked via `claim_clock`): the donor partitioned — or just
        never answered — and the claim must die cleanly.  Nothing was
        re-celled for a pending claim, so rollback is pure
        bookkeeping; the claimant re-claims after heal.  Returns the
        number rolled back."""
        rolled = 0
        with self._lock:
            for cid in sorted(self.reclaim_claims):
                claim = self.reclaim_claims[cid]
                if claim["state"] != "pending" or \
                        self.claim_clock < claim["deadline"]:
                    continue
                if claim.get("granted"):
                    # FRACTIONAL close: a multi-node claim partially
                    # filled at its deadline keeps what moved (every
                    # granted node was already atomically re-celled)
                    # and abandons the remainder — "granted" with
                    # fractional=True, counted as an expiry.
                    claim["state"] = "granted"
                    claim["fractional"] = True
                    claim["resolved"] = self.claim_clock
                    self.reclaim_expired += 1
                    self._on_reclaim({
                        "op": "reclaim-expire", "claim": cid,
                        "to": claim["to"], "from": claim["from"],
                        "granted": len(claim["granted"]),
                        "wanted": int(claim.get("nodes", 1)),
                    })
                    continue
                claim["state"] = "rolled-back"
                claim["resolved"] = self.claim_clock
                self.reclaim_rolled_back += 1
                rolled += 1
                self._on_reclaim({
                    "op": "reclaim-rollback", "claim": cid,
                    "to": claim["to"], "from": claim["from"],
                })
        return rolled
