"""ExternalCluster: an authoritative out-of-process-shaped cluster.

The stand-in for a real apiserver+kubelets in adapter tests and demos
(≙ the role a kind/minikube cluster plays for the reference's e2e suite,
test/e2e/util.go · initTestContext).  It owns the truth about pods,
nodes, groups and queues, serves the JSON-lines wire protocol over a
duplex stream, and reacts to scheduler writes the way a cluster would:

* bind   → pod becomes Bound on the node (MODIFIED event), unless the
           node is gone or a failure is injected → error response;
* evict  → pod returns to Pending (MODIFIED event) — the controller
           recreating the workload, like the in-process simulator;
* tick() → Bound pods start Running (kubelet heartbeat analog);
* lease verbs (acquire/renew/release with TTL) → the resourcelock of
  the reference's leader election (app/server.go · leaderelection.
  RunOrDie): the lock object lives on the CLUSTER, so standbys on
  other hosts contend for it over the wire (VERDICT r3 next #5).
  Every acquire that changes hands (or revives an expired lease)
  MINTS a monotonically increasing fencing EPOCH, returned in the
  response (≙ the Lease's ``spec.leaseTransitions``); data-plane
  writes carrying an ``epoch`` field are REJECTED with a structured
  ``StaleEpoch`` error unless it matches the current epoch — a
  deposed leader's in-flight flush workers can never land zombie
  writes after a successor takes over
  (doc/design/failover-fencing.md).

Multiple scheduler sessions may attach (leader + standbys, like
replicas sharing one apiserver); watch events broadcast to all of
them, and a late-attaching session gets a LIST replay first
(≙ informer re-list on connect — stateless recovery).

The scheduler side never touches this object directly — everything
crosses the wire, so a test that passes here proves the adapter path
end-to-end (VERDICT r1 item 4: schedule a world the scheduler only
learns about through the stream).
"""

from __future__ import annotations

import collections
import json
import socket
import threading
import time
from typing import IO

from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.cache.cluster import Node, Pod, PodGroup, Queue
from kube_batch_tpu.client.codec import (
    encode_node,
    encode_pod,
    encode_pod_group,
    encode_queue,
)


def stream_pair() -> tuple[IO[str], IO[str], IO[str], IO[str]]:
    """(cluster_r, cluster_w, scheduler_r, scheduler_w) over a local
    socketpair — the two ends of the 'network'."""
    a, b = socket.socketpair()
    return (
        a.makefile("r", encoding="utf-8"),
        a.makefile("w", encoding="utf-8"),
        b.makefile("r", encoding="utf-8"),
        b.makefile("w", encoding="utf-8"),
    )


class ExternalCluster:
    def __init__(
        self,
        reader: IO[str] | None = None,
        writer: IO[str] | None = None,
        history: int = 1000,
    ) -> None:
        self._lock = threading.RLock()
        self._sessions: list[tuple[IO[str], IO[str]]] = []
        # -- watch-resume bookkeeping (≙ apiserver resourceVersions +
        # the bounded watch cache a reflector resumes from): every
        # broadcast event gets a monotonically increasing RV and lands
        # in a bounded history ring; a reconnecting session asks for
        # everything after its last-seen RV ("watchResume") and gets
        # either the missed tail or a 410-style "gone" forcing a
        # full re-list.
        self._rv = 0
        self._history: "collections.deque[dict]" = collections.deque(
            maxlen=history
        )
        self.pods: dict[str, Pod] = {}
        # (namespace, name) → uid index for the k8s dialect's
        # path-addressed writes; pods are never removed (evict returns
        # them to Pending), so submit() is the only maintenance site.
        self._pods_by_name: dict[tuple[str, str], str] = {}
        self.nodes: dict[str, Node] = {}
        self.groups: dict[str, PodGroup] = {}
        self.queues: dict[str, Queue] = {}
        self.binds: list[tuple[str, str]] = []
        self.evictions: list[tuple[str, str]] = []
        self.status_updates: list[PodGroup] = []
        # k8s-dialect write log: every apiserver-shaped request as it
        # arrived on the wire — (verb, path, object) — so tests can
        # assert the exact shapes a real apiserver would receive.
        self.k8s_writes: list[tuple[str, str, dict]] = []
        self.k8s_events: list[dict] = []  # core/v1 Event objects POSTed
        self.fail_bind_pods: set[str] = set()  # inject failures by pod name
        self._threads: list[threading.Thread] = []
        self._started = False
        # -- the resourcelock (≙ resourcelock.LeaseLock on the apiserver)
        self.lease_holder: str | None = None
        self.lease_expires: float = 0.0
        # Fencing epoch: bumped on every acquire that changes hands or
        # revives an expired lease (≙ leaseTransitions), NEVER reset —
        # a write stamped with an older epoch is a zombie from a
        # deposed leader and is rejected below.
        self.lease_epoch: int = 0
        self.epoch_holders: dict[int, str] = {}  # audit: epoch → holder
        self.stale_epoch_rejections = 0
        # The leader's mirrored operational-state snapshot (statestore
        # HA adoption): last-write-wins, epoch-fenced on write like
        # every data-plane verb, readable by any contender.  The k8s
        # dialect lands here too (ConfigMap-shaped write).
        self.state_snapshot: dict | None = None
        # The leader's mirrored AOT compile artifacts
        # (doc/design/compile-artifacts.md): entry-name → payload,
        # merged per put (a bank holds MANY programs, unlike the
        # single statestore snapshot), bounded FIFO so a pathological
        # shape churn cannot grow the control plane unboundedly.
        # Epoch-fenced on write, readable by any contender; the k8s
        # dialect lands here too (ConfigMap-shaped write).
        self.compile_artifacts: dict[str, dict] = {}
        if reader is not None and writer is not None:
            self.attach(reader, writer)

    # -- sessions -------------------------------------------------------
    def attach(self, reader: IO[str], writer: IO[str]) -> None:
        """Register one scheduler session (reader serves its write
        requests once start()ed; writer receives broadcast events)."""
        with self._lock:
            self._sessions.append((reader, writer))
            if self._started:  # already serving: start this one too
                t = threading.Thread(
                    target=self._serve, args=(reader,), daemon=True
                )
                self._threads.append(t)
                t.start()

    def replay(self, writer: IO[str]) -> None:
        """LIST replay for a late-attaching session: every current
        object as ADDED, then SYNC carrying the collection's
        resourceVersion (≙ informer re-list + HasSynced; the reflector
        resumes its watch from the LIST's RV)."""
        with self._lock:
            for q in self.queues.values():
                self._emit_to(writer, "ADDED", "Queue", encode_queue(q))
            for n in self.nodes.values():
                self._emit_to(writer, "ADDED", "Node", encode_node(n))
            for g in self.groups.values():
                self._emit_to(writer, "ADDED", "PodGroup", encode_pod_group(g))
            for p in self.pods.values():
                self._emit_to(writer, "ADDED", "Pod", encode_pod(p))
            self._emit_to(writer, None, None, None, raw={
                "type": "SYNC", "resourceVersion": self._rv,
            })

    # -- wire out -------------------------------------------------------
    def _emit_to(self, writer, mtype, kind, obj, raw: dict | None = None):
        msg = raw if raw is not None else {
            "type": mtype, "kind": kind, "object": obj,
        }
        try:
            writer.write(json.dumps(msg) + "\n")
            writer.flush()
        except (OSError, ValueError):
            pass  # dead session; its reader thread is ending too

    def _emit(self, mtype: str, kind: str, obj: dict) -> None:
        with self._lock:
            self._rv += 1
            msg = {
                "type": mtype, "kind": kind, "object": obj,
                "resourceVersion": self._rv,
            }
            self._history.append(msg)
            for _r, w in self._sessions:
                self._emit_to(w, None, None, None, raw=msg)

    def _respond(
        self, writer: IO[str], rid: int, ok: bool, error: str = "",
        code: str | None = None, extra: dict | None = None,
    ) -> None:
        msg: dict = {"type": "RESPONSE", "id": rid, "ok": ok}
        if error:
            msg["error"] = error
        if code:
            # Structured error class (today: "StaleEpoch") so clients
            # classify without parsing the human-readable message.
            msg["code"] = code
        if extra:
            msg.update(extra)
        with self._lock:
            self._emit_to(writer, None, None, None, raw=msg)

    def sync(self) -> None:
        """Mark the initial LIST replay complete (≙ informer HasSynced)."""
        with self._lock:
            for _r, w in self._sessions:
                self._emit_to(w, None, None, None, raw={
                    "type": "SYNC", "resourceVersion": self._rv,
                })

    # -- authoritative world mutations (all emit watch events) ----------
    def add_node(self, node: Node) -> None:
        with self._lock:
            self.nodes[node.name] = node
            self._emit("ADDED", "Node", encode_node(node))

    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self.nodes.pop(name, None)
            if node is None:
                return
            # Pods on the dead node go Pending again (controller restart).
            for pod in self.pods.values():
                if pod.node == name:
                    pod.node = None
                    pod.status = TaskStatus.PENDING
                    self._emit("MODIFIED", "Pod", encode_pod(pod))
            self._emit("DELETED", "Node", encode_node(node))

    def add_queue(self, queue: Queue) -> None:
        with self._lock:
            self.queues[queue.name] = queue
            self._emit("ADDED", "Queue", encode_queue(queue))

    def submit(self, group: PodGroup, pods: list[Pod]) -> None:
        with self._lock:
            self.groups[group.name] = group
            self._emit("ADDED", "PodGroup", encode_pod_group(group))
            for pod in pods:
                pod.group = group.name
                self.pods[pod.uid] = pod
                key = (pod.namespace, pod.name)
                # First submission wins, matching the linear scan this
                # index replaced (dict iteration = insertion order).
                self._pods_by_name.setdefault(key, pod.uid)
                self._emit("ADDED", "Pod", encode_pod(pod))

    def tick(self) -> None:
        """Bound → Running (kubelet starting containers)."""
        with self._lock:
            for pod in self.pods.values():
                if pod.status == TaskStatus.BOUND:
                    pod.status = TaskStatus.RUNNING
                    self._emit("MODIFIED", "Pod", encode_pod(pod))

    def delete_pod(self, uid: str) -> None:
        """Remove a pod for good (a controller garbage-collecting a
        finished workload — unlike evict, nothing recreates it)."""
        with self._lock:
            pod = self.pods.pop(uid, None)
            if pod is None:
                return
            key = (pod.namespace, pod.name)
            if self._pods_by_name.get(key) == uid:
                self._pods_by_name.pop(key, None)
            self._emit("DELETED", "Pod",
                       {"uid": pod.uid, "name": pod.name})

    def complete_group(self, name: str) -> None:
        """A whole job finishes: its pods and PodGroup are deleted
        (the controller reaping a Succeeded workload) — the watch
        stream carries the teardown like any other churn."""
        with self._lock:
            group = self.groups.pop(name, None)
            for uid in [u for u, p in self.pods.items() if p.group == name]:
                self.delete_pod(uid)
            if group is not None:
                self._emit("DELETED", "PodGroup", encode_pod_group(group))

    def expire_history(self) -> None:
        """Drop the watch-event history ring (≙ apiserver etcd
        compaction): the next `watchResume` over any missed tail is
        forced onto the 410-Gone path and the client must re-list."""
        with self._lock:
            self._history.clear()

    # -- the serve loop (scheduler write requests) ----------------------
    def start(self) -> "ExternalCluster":
        with self._lock:
            self._started = True
            for reader, _w in self._sessions:
                t = threading.Thread(
                    target=self._serve, args=(reader,), daemon=True
                )
                self._threads.append(t)
                t.start()
        return self

    def _writer_for(self, reader: IO[str]) -> IO[str] | None:
        with self._lock:
            for r, w in self._sessions:
                if r is reader:
                    return w
        return None

    def _serve(self, reader: IO[str]) -> None:
        writer = self._writer_for(reader)
        try:
            for line in reader:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue  # one garbled request must not kill serving
                if msg.get("type") != "REQUEST":
                    continue
                self._handle(writer, msg)
        except (OSError, ValueError):
            # ValueError = iterating a concurrently-closed file object;
            # JSONDecodeError never reaches here (handled per line).
            pass  # scheduler hung up
        finally:
            # Prune the dead session: repeated failovers must not leave
            # broadcasts writing to an ever-growing list of corpses.
            with self._lock:
                self._sessions = [
                    (r, w) for r, w in self._sessions if r is not reader
                ]

    # -- lease arbitration (≙ resourcelock acquire-or-renew) ------------
    def _handle_lease(self, writer, verb: str, msg: dict) -> None:
        rid, holder = msg["id"], msg.get("holder", "")
        now = time.monotonic()
        if verb == "releaseLease":
            if self.lease_holder == holder:
                self.lease_holder = None
                self.lease_expires = 0.0
                # The epoch is NOT reset: monotonicity is the fencing
                # guarantee, and the next acquire mints a fresh one.
            self._respond(writer, rid, True)
            return
        ttl = float(msg.get("ttl", 15.0))
        expired = now >= self.lease_expires
        if verb == "renewLease" and self.lease_holder != holder:
            # A renewal after the lease was TAKEN must fail: the old
            # holder has to stand down (≙ RunOrDie's OnStoppedLeading).
            # A merely-expired-but-unclaimed lease renews fine — the
            # holder was just slow, and nobody else is leading.
            self._respond(
                writer, rid, False,
                f"lease lost (held by {self.lease_holder!r})",
            )
            return
        if verb == "acquireLease" and not expired and self.lease_holder not in (
            None, holder
        ):
            self._respond(
                writer, rid, False,
                f"lease held by {self.lease_holder!r} for "
                f"{self.lease_expires - now:.1f}s",
            )
            return
        if verb == "acquireLease" and (
            self.lease_holder != holder or expired or self.lease_epoch == 0
        ):
            # A change of hands (or reviving an expired lease — even by
            # its previous holder: its pre-expiry in-flight writes are
            # no longer trustworthy) mints the next epoch.  An
            # idempotent re-acquire by the live current holder keeps
            # its epoch.
            self.lease_epoch += 1
            self.epoch_holders[self.lease_epoch] = holder
            self._on_epoch_advance(self.lease_epoch, holder)
        self.lease_holder = holder
        self.lease_expires = now + ttl
        self._respond(writer, rid, True,
                      extra={"epoch": self.lease_epoch})

    def expire_lease(self) -> None:
        """Force the current lease to expire NOW (≙ the holder's
        renewals stopping and the TTL running out — a leader crash as
        the cluster observes it): the next acquire by anyone succeeds
        and mints a higher epoch.  The holder field is left as the
        corpse's identity, exactly like a real resourcelock."""
        with self._lock:
            self.lease_expires = 0.0

    # Hooks a subclass (chaos/faults.ChaosCluster) can instrument.
    def _on_epoch_advance(self, epoch: int, holder: str) -> None:
        pass

    def _on_stale_reject(self, msg: dict) -> None:
        pass

    @property
    def FENCED_VERBS(self):  # noqa: N802 — constant-shaped
        """Data-plane verbs subject to epoch fencing — the ONE
        canonical set, shared with the client's local fence
        (client/adapter.py · FENCED_VERBS; lazy import: adapter
        imports the cache at load time).  Watch/lease/list verbs and
        the breaker's `ping` probe are NOT fenced: a standby must
        keep ingesting and probing, and the elector itself is how a
        deposed leader gets a NEW epoch."""
        from kube_batch_tpu.client.adapter import FENCED_VERBS

        return FENCED_VERBS

    def _check_epoch(self, writer, msg: dict) -> bool:
        """True when the request may proceed.  A data-plane write
        stamped with a non-current epoch is a zombie — rejected with
        the structured StaleEpoch code (no retry: the caller's
        leadership is gone, not its wire)."""
        epoch = msg.get("epoch")
        if epoch is None:
            return True  # unfenced caller (no leader election wired)
        verb = msg.get("verb")
        if "path" not in msg and verb not in self.FENCED_VERBS:
            return True
        if int(epoch) == self.lease_epoch:
            return True
        self.stale_epoch_rejections += 1
        self._on_stale_reject(msg)
        self._respond(
            writer, msg["id"], False,
            f"stale epoch {epoch} (current epoch "
            f"{self.lease_epoch}, holder {self.lease_holder!r})",
            code="StaleEpoch",
        )
        return False

    # -- apiserver-dialect writes (client/k8s_write.py shapes) ----------
    def _find_pod(self, namespace: str, name: str) -> Pod | None:
        """O(1) by-name lookup for the k8s dialect's path-addressed
        writes: a 47.5k-pod gang commit issues one of these per
        Binding POST, and a linear scan under the global lock would
        make the fixture consumer quadratic in cluster size — the
        bottleneck the scheduler's bind fan-out exists to remove."""
        key = (namespace, name)
        uid = self._pods_by_name.get(key)
        pod = self.pods.get(uid) if uid is not None else None
        if pod is not None:
            return pod
        # Index miss: tests (and uid churn — a controller recreating a
        # same-named pod) mutate self.pods directly, so fall back to
        # the scan the index replaced and repair the entry.
        for pod in self.pods.values():
            if pod.namespace == namespace and pod.name == name:
                self._pods_by_name[key] = pod.uid
                return pod
        return None

    def _bind_pod(self, writer, rid: int, pod: Pod | None,
                  node_name: str) -> None:
        """Shared bind semantics for both wire dialects."""
        if pod is None:
            self._respond(writer, rid, False, "pod not found")
        elif pod.name in self.fail_bind_pods:
            self._respond(writer, rid, False, "injected bind failure")
        elif node_name not in self.nodes:
            self._respond(writer, rid, False, f"node {node_name} not found")
        else:
            pod.node = node_name
            pod.status = TaskStatus.BOUND
            self.binds.append((pod.name, node_name))
            self._respond(writer, rid, True)
            self._emit("MODIFIED", "Pod", encode_pod(pod))

    def _evict_pod(self, writer, rid: int, pod: Pod | None,
                   reason: str) -> None:
        if pod is None:
            self._respond(writer, rid, False, "pod not found")
        else:
            pod.node = None
            pod.status = TaskStatus.PENDING
            self.evictions.append((pod.name, reason))
            self._respond(writer, rid, True)
            self._emit("MODIFIED", "Pod", encode_pod(pod))

    def _handle_k8s(self, writer, msg: dict) -> None:
        """Route an apiserver-shaped request (verb + resource path +
        k8s body) the way a real apiserver would, validating the shapes
        the reference's REST calls carry."""
        import re

        verb, rid = msg.get("verb"), msg["id"]
        path, obj = msg.get("path", ""), msg.get("object") or {}
        self.k8s_writes.append((verb, path, obj))

        m = re.fullmatch(r"/api/v1/namespaces/([^/]+)/pods/([^/]+)/binding",
                         path)
        if m and verb == "create":
            if obj.get("kind") != "Binding" or \
                    obj.get("target", {}).get("kind") != "Node":
                self._respond(writer, rid, False,
                              "malformed Binding object")
                return
            if obj.get("metadata", {}).get("name") != m.group(2):
                self._respond(writer, rid, False,
                              "Binding name does not match path")
                return
            self._bind_pod(writer, rid, self._find_pod(*m.groups()),
                           obj["target"].get("name", ""))
            return

        m = re.fullmatch(r"/api/v1/namespaces/([^/]+)/pods/([^/]+)", path)
        if m and verb == "delete":
            pod = self._find_pod(*m.groups())
            want_uid = (obj.get("preconditions") or {}).get("uid")
            if pod is not None and want_uid and pod.uid != want_uid:
                # ≙ apiserver 409: the named pod is not the one the
                # eviction decision was made against.
                self._respond(writer, rid, False,
                              "precondition failed: uid mismatch")
                return
            self._evict_pod(writer, rid, pod, "k8s-delete")
            return

        m = re.fullmatch(
            r"/apis/[^/]+/v1alpha\d/namespaces/([^/]+)/"
            r"podgroups/([^/]+)/status", path,
        )
        if m and verb == "update":
            if obj.get("kind") != "PodGroup" or "status" not in obj:
                self._respond(writer, rid, False,
                              "malformed PodGroup status object")
                return
            name, status = m.group(2), obj["status"]
            group = self.groups.get(name)
            if group is not None:
                from kube_batch_tpu.api.types import (
                    PodGroupCondition,
                    PodGroupPhase,
                )

                group.phase = PodGroupPhase(status.get("phase", "Pending"))
                group.running = int(status.get("running", 0))
                group.succeeded = int(status.get("succeeded", 0))
                group.failed = int(status.get("failed", 0))
                group.conditions = [
                    PodGroupCondition(
                        type=c.get("type", "Note"),
                        status=c.get("status") == "True",
                        reason=c.get("reason", ""),
                        message=c.get("message", ""),
                    )
                    for c in status.get("conditions", [])
                ]
                self.status_updates.append(group)
            self._respond(writer, rid, group is not None,
                          "" if group is not None else "podgroup not found")
            return

        m = re.fullmatch(r"/api/v1/nodes/([^/]+)", path)
        if m and verb in ("patch", "update"):
            # ≙ kubectl cordon/uncordon: spec.unschedulable PATCH from
            # the health ledger's cordon sink.  The cluster mutates the
            # node and broadcasts MODIFIED, so every attached session
            # (and the writer itself, symmetrically) observes the
            # cordon on the watch stream.
            node = self.nodes.get(m.group(1))
            if node is None:
                self._respond(writer, rid, False,
                              f"node {m.group(1)} not found")
                return
            spec = obj.get("spec") or {}
            if "unschedulable" not in spec:
                self._respond(writer, rid, False,
                              "patch carries no spec.unschedulable")
                return
            node.unschedulable = bool(spec["unschedulable"])
            self._respond(writer, rid, True)
            self._emit("MODIFIED", "Node", encode_node(node))
            return

        m = re.fullmatch(
            r"/api/v1/namespaces/([^/]+)/configmaps/([^/]+)", path
        )
        if m and verb in ("create", "update", "patch"):
            from kube_batch_tpu.client.k8s_write import (
                COMPILE_CONFIGMAP_NAME,
                COMPILE_CONFIGMAP_NAMESPACE,
                STATE_CONFIGMAP_NAME,
                STATE_CONFIGMAP_NAMESPACE,
            )

            if m.groups() == (COMPILE_CONFIGMAP_NAMESPACE,
                              COMPILE_CONFIGMAP_NAME):
                # The artifact bank's mirror in apiserver dialect: a
                # ConfigMap whose data maps entry-name → one JSON
                # entry payload (epoch-fenced by path above).  Each
                # write MERGES its keys — the bank holds many
                # programs, and a patch must not clobber its siblings.
                from kube_batch_tpu.compile_cache import (
                    payloads_from_configmap_data,
                )

                data = obj.get("data")
                if obj.get("kind") != "ConfigMap" or \
                        not isinstance(data, dict):
                    self._respond(writer, rid, False,
                                  "malformed compile-artifacts "
                                  "ConfigMap")
                    return
                for payload in payloads_from_configmap_data(data):
                    self._merge_compile_artifact(payload)
                self._respond(writer, rid, True)
                return
            if m.groups() != (STATE_CONFIGMAP_NAMESPACE,
                              STATE_CONFIGMAP_NAME):
                # Only the dedicated control-plane objects route here —
                # an unrelated ConfigMap write must not clobber the
                # snapshot a successor will adopt.
                self._respond(writer, rid, False,
                              f"unhandled k8s request {verb} {path}")
                return
            # The statestore's HA mirror in apiserver dialect: a
            # ConfigMap whose data.state carries the compacted
            # operational snapshot (epoch-fenced by path above).
            import json as _json

            raw = (obj.get("data") or {}).get("state")
            if obj.get("kind") != "ConfigMap" or not isinstance(raw, str):
                self._respond(writer, rid, False,
                              "malformed state ConfigMap")
                return
            try:
                payload = _json.loads(raw)
            except _json.JSONDecodeError:
                self._respond(writer, rid, False,
                              "state ConfigMap data.state is not JSON")
                return
            self.state_snapshot = (
                payload if isinstance(payload, dict) else None
            )
            self._respond(writer, rid, True)
            return

        m = re.fullmatch(r"/api/v1/namespaces/([^/]+)/events", path)
        if m and verb == "create":
            if obj.get("kind") != "Event" or "involvedObject" not in obj:
                self._respond(writer, rid, False, "malformed Event object")
                return
            self.k8s_events.append(obj)
            self._respond(writer, rid, True)
            return

        self._respond(writer, rid, False,
                      f"unhandled k8s request {verb} {path}")

    #: Mirror bound: oldest entries drop past this — a pathological
    #: shape churn must not grow the control-plane object unboundedly
    #: (the local bank on disk is the full record).
    COMPILE_ARTIFACTS_MAX = 32

    def _merge_compile_artifact(self, payload: dict) -> None:
        """Merge one mirrored bank entry (keyed by its entry name;
        re-puts of the same key replace in place), bounded FIFO."""
        name = str(payload.get("name") or f"anon-{len(self.compile_artifacts)}")
        self.compile_artifacts.pop(name, None)
        self.compile_artifacts[name] = payload
        while len(self.compile_artifacts) > self.COMPILE_ARTIFACTS_MAX:
            self.compile_artifacts.pop(
                next(iter(self.compile_artifacts))
            )

    # -- watch resume (≙ reflector re-watch from last RV / 410 Gone) ----
    def _handle_watch_resume(self, writer, rid: int, since: int) -> None:
        """Serve the missed event tail when the history ring still
        covers `since`; otherwise answer the 410-Gone analog and the
        client must re-list.  Either way a SYNC trails the replay so
        the session's adapter re-arms its sync gate."""
        if since > self._rv:
            # The client is AHEAD of us: this cluster incarnation was
            # restarted (fresh RV space) — its history cannot mean what
            # the client thinks.  Force the re-list, like an apiserver
            # answering 410 for an unknown RV.
            self._respond(
                writer, rid, False,
                f"410 gone: rv {since} is from another watch incarnation",
            )
            return
        if since < self._rv and (
            not self._history or self._history[0]["resourceVersion"] > since + 1
        ):
            # The tail the client missed has partly fallen out of the
            # ring — replaying the remainder would silently skip events.
            self._respond(
                writer, rid, False,
                f"410 gone: watch history starts after rv {since}",
            )
            return
        self._respond(writer, rid, True)
        for past in self._history:
            if past["resourceVersion"] > since:
                self._emit_to(writer, None, None, None, raw=past)
        self._emit_to(writer, None, None, None, raw={
            "type": "SYNC", "resourceVersion": self._rv,
        })

    def _handle(self, writer: IO[str], msg: dict) -> None:
        verb, rid = msg.get("verb"), msg["id"]
        with self._lock:
            if not self._check_epoch(writer, msg):
                return  # zombie write from a deposed epoch: rejected
            if "path" in msg:  # apiserver-dialect write
                self._handle_k8s(writer, msg)
            elif verb == "watchResume":
                self._handle_watch_resume(writer, rid,
                                          int(msg.get("since", 0)))
            elif verb == "list":
                self._respond(writer, rid, True)
                self.replay(writer)
            elif verb in ("acquireLease", "renewLease", "releaseLease"):
                self._handle_lease(writer, verb, msg)
            elif verb == "bind":
                self._bind_pod(
                    writer, rid, self.pods.get(msg["pod"]), msg["node"]
                )
            elif verb == "evict":
                self._evict_pod(
                    writer, rid, self.pods.get(msg["pod"]),
                    msg.get("reason", ""),
                )
            elif verb == "ping":
                # Health probe (the wire breaker's half-open check):
                # answer, touch nothing.
                self._respond(writer, rid, True)
            elif verb == "putStateSnapshot":
                # The statestore's HA mirror (epoch-fenced above):
                # last-write-wins, no watch event — control-plane
                # metadata, not cluster state.
                obj = msg.get("object")
                self.state_snapshot = obj if isinstance(obj, dict) else None
                self._respond(writer, rid, True)
            elif verb == "getStateSnapshot":
                self._respond(writer, rid, True,
                              extra={"object": self.state_snapshot})
            elif verb == "putCompileArtifact":
                # The AOT artifact bank's cluster-side mirror
                # (epoch-fenced above): one entry merged per put, no
                # watch event — control-plane metadata like the state
                # snapshot, but a SET (a bank holds many programs).
                obj = msg.get("object")
                if not isinstance(obj, dict):
                    self._respond(writer, rid, False,
                                  "malformed compile artifact")
                else:
                    self._merge_compile_artifact(obj)
                    self._respond(writer, rid, True)
            elif verb == "getCompileArtifact":
                self._respond(writer, rid, True, extra={
                    "object": list(self.compile_artifacts.values()),
                })
            elif verb == "updatePodGroup":
                from kube_batch_tpu.client.codec import decode_pod_group

                group = decode_pod_group(msg["object"])
                if group.name in self.groups:
                    self.groups[group.name] = group
                self.status_updates.append(group)
                self._respond(writer, rid, True)
            else:
                self._respond(writer, rid, False, f"unknown verb {verb}")
