"""Cluster client layer: the L0 adapter between an external cluster and
the scheduler cache.

Reference counterpart: pkg/client/ (the generated clientset/informers/
listers) + cache/event_handlers.go (informer fan-in) — the machinery
that turns apiserver watch streams into cache events and scheduler
decisions into REST writes.  Here the wire protocol is JSON-lines over
any duplex byte stream (see `kube_batch_tpu.client.adapter`): watch
events flow in, bind/evict/status writes flow out with request/response
correlation — the same shape as client-go's informer + REST round trips,
without the Kubernetes dependency.
"""

from kube_batch_tpu.client.adapter import (
    CELL_LABEL,
    CellScopeError,
    LeaseElector,
    StaleEpochError,
    StreamBackend,
    WatchAdapter,
    resume_session,
)
from kube_batch_tpu.client.external import ExternalCluster
from kube_batch_tpu.client.failover import (
    reconcile_takeover,
    resume_leadership,
    stand_down,
)
from kube_batch_tpu.client.k8s import K8sWatchAdapter

__all__ = [
    "CELL_LABEL",
    "CellScopeError",
    "WatchAdapter",
    "StaleEpochError",
    "StreamBackend",
    "ExternalCluster",
    "LeaseElector",
    "K8sWatchAdapter",
    "reconcile_takeover",
    "resume_leadership",
    "resume_session",
    "stand_down",
]
