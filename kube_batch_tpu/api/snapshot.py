"""Dense tensor snapshot of the cluster — the device-side ClusterInfo.

Reference counterpart: pkg/scheduler/api/cluster_info.go · ClusterInfo
(maps of JobInfo/NodeInfo/QueueInfo) plus the per-object accounting in
job_info.go / node_info.go.  The TPU-native design replaces those maps
with one immutable pytree of padded, statically-shaped arrays: every
plugin and action is a pure function `SnapshotTensors -> tensors`, so the
whole scheduling cycle jits into a single XLA program.

Shape legend (all padded):
    T — tasks (pods)        J — jobs (pod groups)
    N — nodes               Q — queues
    R — resource dims       L — label vocab     V — taint vocab
    P — host-port vocab

Label/taint/port *vocabularies* are the TPU answer to the reference's
string-keyed selector/taint matching (plugins/predicates/predicates.go):
the packer interns strings into per-snapshot integer vocabularies, and
matching becomes small matmuls over multi-hot matrices — MXU work instead
of per-node string comparisons.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from kube_batch_tpu.api.types import (
    ALLOCATED_STATUSES,
    READY_STATUSES,
    VALID_STATUSES,
    TaskStatus,
)

# Sentinel index for "no node / no job / no queue".
NONE_IDX = -1


@struct.dataclass
class SnapshotTensors:
    """One consistent, immutable view of the cluster as device arrays.

    Produced by `kube_batch_tpu.cache.packer.pack_snapshot`; consumed by
    every plugin/action.  Padding rows have mask == False and are inert in
    all kernels (requests 0, capacities 0, job/queue index NONE_IDX).
    """

    # -- tasks ----------------------------------------------------------
    task_req: jax.Array        # f32[T, R]  resource request (Resreq)
    task_state: jax.Array      # i32[T]     TaskStatus value
    task_job: jax.Array        # i32[T]     owning job index (NONE_IDX if none)
    task_node: jax.Array       # i32[T]     current node index (NONE_IDX if none)
    task_prio: jax.Array       # f32[T]     pod priority
    task_order: jax.Array      # i32[T]     creation-order tiebreak (stable)
    task_mask: jax.Array       # bool[T]    valid (non-padding) row
    task_sel: jax.Array        # f32[T, L]  required node-label selector, multi-hot
    task_pref: jax.Array       # f32[T, L]  preferred node labels, weighted multi-hot
    task_tol: jax.Array        # f32[T, V]  tolerated taints, multi-hot
    task_ports: jax.Array      # f32[T, P]  requested host ports, multi-hot
    task_critical: jax.Array   # bool[T]    conformance-protected (critical) pod
    # inter-pod affinity over the pod-label vocab (K = pod-label vocab)
    task_podlabels: jax.Array  # f32[T, K]  this pod's own labels, multi-hot
    task_aff: jax.Array        # f32[T, K]  required co-location terms (node-level)
    task_anti: jax.Array       # f32[T, K]  required anti-affinity terms (node-level)
    task_podpref: jax.Array    # f32[T, K]  preferred co-location, weighted
    # topology-scoped affinity terms ("zone:app=web"): K2 = topo-term
    # vocab, TK = topology-key vocab, D = domain vocab (nodes sharing a
    # topology label value; nodes missing the label get private
    # fallback domains).  K2 == 0 (static) ⇒ no topo terms in this
    # snapshot and kernels skip the domain math entirely.
    task_aff_topo: jax.Array   # f32[T, K2]  required co-location, by domain
    task_anti_topo: jax.Array  # f32[T, K2]  anti-affinity, by domain
    task_podpref_topo: jax.Array  # f32[T, K2 | 0]  preferred co-location, weighted, by domain (zero-width when no soft topo prefs)
    topo_term_key: jax.Array   # i32[K2]     term → topology-key index
    topo_term_label: jax.Array  # i32[K2]    term → pod-label index (in K)
    node_key_domain: jax.Array  # i32[N, TK] node → domain id per topology key
    domain_mask: jax.Array     # bool[D]    real-domain rows
    # volume feasibility (G = constrained-claim "volume group" vocab):
    # a bound local PV pins the task to one node; an unbound claim's
    # StorageClass restricts it to nodes matching >=1 allowed label.
    # task_vol_node: NONE_IDX = unpinned; -2 = infeasible everywhere
    # (conflicting/unknown claims — diagnosed via fit_errors).
    task_vol_node: jax.Array   # i32[T]
    task_vol_groups: jax.Array  # f32[T, G]  constrained claims mounted
    vol_group_sel: jax.Array   # f32[G, L]  each group's OR-set of labels

    # -- jobs -----------------------------------------------------------
    job_queue: jax.Array       # i32[J]     owning queue index
    job_min: jax.Array         # i32[J]     minMember / MinAvailable
    job_prio: jax.Array        # f32[J]     pod-group priority-class value
    job_order: jax.Array       # i32[J]     creation-order tiebreak
    job_mask: jax.Array        # bool[J]

    # -- nodes ----------------------------------------------------------
    node_cap: jax.Array        # f32[N, R]  allocatable capacity
    node_idle: jax.Array       # f32[N, R]  capacity minus allocated requests
    node_releasing: jax.Array  # f32[N, R]  requests of Releasing tasks
    node_labels: jax.Array     # f32[N, L]  node labels, multi-hot
    node_taints: jax.Array     # f32[N, V]  NoSchedule/NoExecute taints, multi-hot
    node_ports: jax.Array      # f32[N, P]  occupied host ports, multi-hot
    node_ready: jax.Array      # bool[N]    node Ready condition / schedulable
    node_pressure: jax.Array   # f32[N, 3]  memory/disk/PID pressure conditions
    node_mask: jax.Array       # bool[N]

    # -- queues ---------------------------------------------------------
    queue_weight: jax.Array    # f32[Q]     proportional-share weight
    queue_mask: jax.Array      # bool[Q]

    # -- namespaces (S = namespace vocab; ≙ api/namespace_info.go) ------
    task_ns: jax.Array         # i32[T]     owning namespace index
    ns_weight: jax.Array       # f32[S]     fair-share weight (default 1)
    ns_mask: jax.Array         # bool[S]

    # -- pod disruption budgets (B = PDB vocab; ≙ JobInfo.PDB) ----------
    # task_pdbs: multi-hot of EVERY PDB whose selector matches the pod's
    # labels — a pod under several budgets is evictable only if ALL of
    # them survive (intersection semantics, matching how the reference
    # would veto a victim under any one violated budget).
    task_pdbs: jax.Array       # f32[T, B]
    pdb_min: jax.Array         # i32[B]     minAvailable floors

    # -- cluster --------------------------------------------------------
    cluster_total: jax.Array   # f32[R]     sum of allocatable over real nodes
    eps: jax.Array             # f32[R]     per-dim negligibility (LessEqual slack)
    besteffort_eps: jax.Array  # f32[R]     like eps but ∞ on counting dims

    # ------------------------------------------------------------------
    @property
    def num_tasks(self) -> int:
        return self.task_req.shape[0]

    @property
    def num_jobs(self) -> int:
        return self.job_min.shape[0]

    @property
    def num_nodes(self) -> int:
        return self.node_cap.shape[0]

    @property
    def num_queues(self) -> int:
        return self.queue_weight.shape[0]

    @property
    def num_resources(self) -> int:
        return self.task_req.shape[1]

    @property
    def shape_key(self) -> tuple[int, ...]:
        """Compile-cache key: identical keys never trigger a recompile."""
        return (
            self.num_tasks,
            self.num_jobs,
            self.num_nodes,
            self.num_queues,
            self.num_resources,
            self.task_sel.shape[1],
            self.task_tol.shape[1],
            self.task_ports.shape[1],
            self.task_podlabels.shape[1],
            self.task_aff_topo.shape[1],
            self.node_key_domain.shape[1],
            self.domain_mask.shape[0],
            self.task_vol_groups.shape[1],
            self.task_pdbs.shape[1],
            self.task_podpref_topo.shape[1],
        )


# ---------------------------------------------------------------------------
# jit-safe derived quantities (the accounting rules of job_info.go /
# node_info.go expressed as whole-snapshot reductions)
# ---------------------------------------------------------------------------

def future_idle(snap: SnapshotTensors) -> jax.Array:
    """Idle + Releasing per node — what will be free once evictions land.

    Reference: node_info.go · FutureIdle semantics.
    """
    return snap.node_idle + snap.node_releasing


def status_is(task_state: jax.Array, *statuses: TaskStatus) -> jax.Array:
    """bool[T] mask of tasks in any of the given statuses."""
    m = jnp.zeros_like(task_state, dtype=bool)
    for s in statuses:
        m = m | (task_state == int(s))
    return m


def allocated_mask(task_state: jax.Array) -> jax.Array:
    """Tasks occupying node resources (job_info.go · AllocatedStatus)."""
    return status_is(task_state, *ALLOCATED_STATUSES)


def count_per_job(snap: SnapshotTensors, task_mask: jax.Array) -> jax.Array:
    """i32[J]: number of masked tasks per job (padding-safe segment count)."""
    seg = jnp.where(task_mask & snap.task_mask, snap.task_job, snap.num_jobs)
    return jax.ops.segment_sum(
        jnp.ones_like(seg, dtype=jnp.int32), seg, num_segments=snap.num_jobs + 1
    )[: snap.num_jobs]


def sum_req_per_job(snap: SnapshotTensors, task_mask: jax.Array) -> jax.Array:
    """f32[J, R]: summed requests of masked tasks per job."""
    w = (task_mask & snap.task_mask).astype(snap.task_req.dtype)
    seg = jnp.where(task_mask & snap.task_mask, snap.task_job, snap.num_jobs)
    return jax.ops.segment_sum(
        snap.task_req * w[:, None], seg, num_segments=snap.num_jobs + 1
    )[: snap.num_jobs]


def job_ready_counts(
    snap: SnapshotTensors, task_state: jax.Array | None = None
) -> jax.Array:
    """i32[J]: tasks per job already holding resources (ReadyTaskNum).

    Reference: job_info.go · ReadyTaskNum = tasks in allocated statuses
    plus Succeeded.  Pass a live `task_state` (e.g. AllocState's) to
    count against in-cycle placements instead of the snapshot's.
    """
    ts = snap.task_state if task_state is None else task_state
    return count_per_job(snap, status_is(ts, *READY_STATUSES))


def job_valid_counts(
    snap: SnapshotTensors, task_state: jax.Array | None = None
) -> jax.Array:
    """i32[J]: tasks that could still become ready (ValidTaskNum).

    Reference: job_info.go · ValidTaskNum — pending, pipelined, and
    allocated-family tasks all count toward minMember feasibility.
    """
    ts = snap.task_state if task_state is None else task_state
    return count_per_job(snap, status_is(ts, *VALID_STATUSES))


def fits(req: jax.Array, avail: jax.Array, eps: jax.Array) -> jax.Array:
    """Batched LessEqual: does `req` fit into `avail`, with per-dim slack?

    req: f32[..., R], avail: f32[..., R], eps: f32[R] → bool[...].
    Mirrors resource_info.go · LessEqual (see api.resource.less_equal_vec).
    """
    return jnp.all((req <= avail) | (req < eps), axis=-1)


def eps_for(spec_eps: np.ndarray) -> jax.Array:
    """Device copy of the ResourceSpec epsilon vector."""
    return jnp.asarray(spec_eps, dtype=jnp.float32)


# ---------------------------------------------------------------------------
# padding helpers (host side)
# ---------------------------------------------------------------------------

def bucket(n: int, minimum: int = 8) -> int:
    """Round `n` up to a padding bucket (next power of two, ≥ minimum).

    Bucketing bounds the number of distinct `shape_key`s, so the jitted
    cycle recompiles O(log cluster-size) times over a cluster's life —
    the guard-rail SURVEY.md §7 calls out for dynamic pod/node churn.
    """
    b = minimum
    while b < n:
        b *= 2
    return b


def pad_rows(arr: np.ndarray, rows: int, fill: Any = 0) -> np.ndarray:
    """Pad axis 0 of `arr` to `rows` with `fill`."""
    if arr.shape[0] > rows:
        raise ValueError(f"cannot pad {arr.shape[0]} rows down to {rows}")
    if arr.shape[0] == rows:
        return arr
    pad_shape = (rows - arr.shape[0],) + arr.shape[1:]
    return np.concatenate([arr, np.full(pad_shape, fill, dtype=arr.dtype)], axis=0)
