"""Status enums for tasks and pod groups.

Reference counterpart: pkg/scheduler/api/types.go · TaskStatus and
pkg/apis/scheduling/v1alpha1/types.go · PodGroupPhase.  Values are integer
IntEnums because they are carried in device tensors (`task_state: i32[T]`).
"""

from __future__ import annotations

import enum


class TaskStatus(enum.IntEnum):
    """Lifecycle of a schedulable task (≙ one pod).

    Semantics follow pkg/scheduler/api/types.go · TaskStatus:

    * PENDING     — waiting for placement.
    * ALLOCATED   — placed in this session; bind not yet dispatched.
    * PIPELINED   — placed against resources that are still being released
                    (fits FutureIdle but not Idle); no bind until release.
    * BINDING     — bind dispatched to the backend, not yet confirmed.
    * BOUND       — backend confirmed the bind.
    * RUNNING     — the workload is executing on its node.
    * RELEASING   — eviction/termination in flight; resources will free.
    * SUCCEEDED / FAILED — terminal.
    * UNKNOWN     — inconsistent backend state.
    """

    PENDING = 0
    ALLOCATED = 1
    PIPELINED = 2
    BINDING = 3
    BOUND = 4
    RUNNING = 5
    RELEASING = 6
    SUCCEEDED = 7
    FAILED = 8
    UNKNOWN = 9


#: Statuses whose resource request is debited from the node's Idle
#: (reference: pkg/scheduler/api/job_info.go · AllocatedStatus).
ALLOCATED_STATUSES = frozenset(
    {TaskStatus.ALLOCATED, TaskStatus.BINDING, TaskStatus.BOUND, TaskStatus.RUNNING}
)

#: Statuses counting toward the gang-readiness threshold
#: (job_info.go · ReadyTaskNum).  Single source of truth for host
#: accounting (cache.info) and device kernels (api.snapshot).
READY_STATUSES = ALLOCATED_STATUSES | {TaskStatus.SUCCEEDED}

#: Statuses that could still become ready (job_info.go · ValidTaskNum).
VALID_STATUSES = READY_STATUSES | {TaskStatus.PENDING, TaskStatus.PIPELINED}


def allocated_status(status: TaskStatus) -> bool:
    """True if `status` occupies node resources (debits Idle)."""
    return status in ALLOCATED_STATUSES


if hasattr(enum, "StrEnum"):
    _StrEnum = enum.StrEnum
else:  # Python 3.10 (the floor pyproject declares): same semantics
    class _StrEnum(str, enum.Enum):
        def __str__(self) -> str:
            return str(self.value)


class PodGroupPhase(_StrEnum):
    """Phase of a job/pod-group (reference: v1alpha1 · PodGroupPhase)."""

    PENDING = "Pending"
    RUNNING = "Running"
    UNKNOWN = "Unknown"
    INQUEUE = "Inqueue"


import dataclasses as _dataclasses  # noqa: E402 — local to avoid re-export


@_dataclasses.dataclass
class PodGroupCondition:
    """Typed status condition (≙ v1alpha1 · PodGroupCondition:
    Type/Status/Reason/Message).  Supports `"text" in condition` so
    message greps read naturally in tests and logs."""

    type: str                 # e.g. "Unschedulable"
    message: str = ""
    status: bool = True
    reason: str = ""

    def __str__(self) -> str:
        return f"{self.type}: {self.message}"

    def __contains__(self, item: str) -> bool:
        return item in str(self)


@_dataclasses.dataclass
class Event:
    """A structured per-object event record (≙ the Kubernetes Events
    the reference emits through its Recorder): object kind/name, a
    CamelCase reason, a human message, and an aggregation count.
    Supports `"text" in event` for message greps."""

    kind: str                 # "Pod" | "PodGroup" | "Node" | "Scheduler"
    name: str                 # object name ("" for scheduler-level)
    reason: str               # "Bound" | "Evicted" | "BindFailed" | ...
    message: str = ""
    count: int = 1

    def __str__(self) -> str:
        suffix = f" (x{self.count})" if self.count > 1 else ""
        return f"{self.kind}/{self.name} {self.reason}: {self.message}{suffix}"

    def __contains__(self, item: str) -> bool:
        return item in str(self)


#: Annotation-equivalent key linking a task to its group
#: (reference: pkg/apis/scheduling/v1alpha1/types.go · GroupNameAnnotationKey).
GROUP_NAME_KEY = "scheduling.tpu/group-name"
