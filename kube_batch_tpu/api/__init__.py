"""Domain model: resource vectors, task/job/node status, snapshot tensors.

Reference counterpart: pkg/scheduler/api (ClusterInfo/JobInfo/TaskInfo/
NodeInfo/QueueInfo/Resource).  Here the durable representation is a dense
tensor snapshot (`SnapshotTensors`); the host-side object model lives in
`kube_batch_tpu.cache`.
"""

from kube_batch_tpu.api.types import (
    TaskStatus,
    PodGroupPhase,
    ALLOCATED_STATUSES,
    allocated_status,
)
from kube_batch_tpu.api.resource import ResourceSpec, Resource
from kube_batch_tpu.api.snapshot import SnapshotTensors

__all__ = [
    "TaskStatus",
    "PodGroupPhase",
    "ALLOCATED_STATUSES",
    "allocated_status",
    "ResourceSpec",
    "Resource",
    "SnapshotTensors",
]
