"""Resource vector math.

Reference counterpart: pkg/scheduler/api/resource_info.go · Resource
(MilliCPU / Memory / ScalarResources with Add/Sub/Multi/Less/LessEqual/
FitDelta/Diff/SetMaxResource/MinDimensionResource/Clone and min-resource
epsilons).

TPU-first redesign: instead of a struct with named fields plus a scalar
map, a resource is a **fixed-order float vector** over a `ResourceSpec`.
This makes the whole framework's resource algebra identical on host
(NumPy, float64, oracle-grade) and device (jnp, float32, shape `[R]` /
`[T, R]` / `[N, R]`), so every plugin/action computes on resources with
ordinary batched array ops instead of per-field branches.

Units: ``cpu`` is in millicores, ``memory`` in bytes, everything else in
plain counts — matching the reference's MilliCPU/Memory convention.
"""

from __future__ import annotations

import dataclasses
from typing import Mapping

import numpy as np

#: Per-dimension slack under which a quantity is treated as negligible
#: (reference: resource_info.go · minMilliCPU=10, minMemory=10Mi,
#: minMilliScalarResources=10).
_DEFAULT_EPS = {
    "cpu": 10.0,            # 10 millicores
    "memory": float(10 << 20),  # 10 MiB
}
_FALLBACK_EPS = 0.1

#: Bookkeeping dimensions that every pod consumes by definition (a pod
#: always takes one pod slot).  Excluded from best-effort/emptiness
#: classification: the reference's notion of a best-effort pod is "empty
#: Resreq", and pod-count is not part of Resreq there.
COUNTING_RESOURCES = ("pods",)


@dataclasses.dataclass(frozen=True)
class ResourceSpec:
    """Ordered universe of resource dimensions for one cluster.

    The first two dimensions are conventionally ``cpu`` and ``memory``;
    further dimensions are scalar/extended resources (accelerators,
    ``pods`` slots, ...).  All tensors in a snapshot share one spec, so a
    dimension index means the same thing everywhere.
    """

    names: tuple[str, ...] = ("cpu", "memory", "pods", "accelerator")

    def __post_init__(self) -> None:
        if len(set(self.names)) != len(self.names):
            raise ValueError(f"duplicate resource names: {self.names}")

    @property
    def num(self) -> int:
        return len(self.names)

    def index(self, name: str) -> int:
        return self.names.index(name)

    def pod_vec(self, pod) -> np.ndarray:
        """Memoizing `vec` over a Pod's request (see cluster.Pod.req_vec):
        computed once per pod lifetime, shared by host accounting and the
        per-cycle snapshot packer.  The memo is keyed on this spec's
        dimension order, so a pod crossing into a differently-ordered
        spec recomputes instead of silently returning swapped dims."""
        memo = pod.req_vec
        if memo is not None and memo[0] is self.names:
            return memo[1]
        v = self.vec(pod.request)
        pod.req_vec = (self.names, v)
        return v

    @property
    def eps(self) -> np.ndarray:
        """Per-dimension negligibility thresholds, shape [R]."""
        return np.array(
            [_DEFAULT_EPS.get(n, _FALLBACK_EPS) for n in self.names], dtype=np.float64
        )

    @property
    def besteffort_eps(self) -> np.ndarray:
        """Like `eps`, but counting dimensions (pod slots) never disqualify
        a request from being best-effort.  Used by the backfill action's
        device-side candidate mask: best-effort ⇔ all(req < besteffort_eps).
        """
        return np.array(
            [
                np.inf if n in COUNTING_RESOURCES else _DEFAULT_EPS.get(n, _FALLBACK_EPS)
                for n in self.names
            ],
            dtype=np.float64,
        )

    def vec(self, quantities: Mapping[str, float] | None = None, **kw: float) -> np.ndarray:
        """Build a dense [R] vector from a name→quantity mapping.

        Unknown names raise — a spec mismatch is a config error, not a
        silent drop.
        """
        out = np.zeros(self.num, dtype=np.float64)
        merged = dict(quantities or {})
        merged.update(kw)
        for name, q in merged.items():
            out[self.index(name)] = float(q)
        return out

    def resource(self, quantities: Mapping[str, float] | None = None, **kw: float) -> "Resource":
        return Resource(self, self.vec(quantities, **kw))


@dataclasses.dataclass
class Resource:
    """A concrete resource amount over a `ResourceSpec`.

    Thin, host-side convenience wrapper; the hot path uses the raw
    vectors.  Arithmetic returns new objects (value semantics, like the
    reference's Clone-then-mutate idiom but immutable).
    """

    spec: ResourceSpec
    vec: np.ndarray

    def __post_init__(self) -> None:
        self.vec = np.asarray(self.vec, dtype=np.float64)
        if self.vec.shape != (self.spec.num,):
            raise ValueError(f"vector shape {self.vec.shape} != [{self.spec.num}]")

    # -- constructors ----------------------------------------------------
    @classmethod
    def zero(cls, spec: ResourceSpec) -> "Resource":
        return cls(spec, np.zeros(spec.num, dtype=np.float64))

    def clone(self) -> "Resource":
        return Resource(self.spec, self.vec.copy())

    # -- accessors -------------------------------------------------------
    def get(self, name: str) -> float:
        return float(self.vec[self.spec.index(name)])

    def as_dict(self) -> dict[str, float]:
        return {n: float(v) for n, v in zip(self.spec.names, self.vec)}

    @property
    def is_empty(self) -> bool:
        """All dimensions below their negligibility threshold.

        Reference: resource_info.go · IsEmpty — the predicate that makes a
        task *best-effort* (eligible for the backfill action).
        """
        return bool(np.all(self.vec < self.spec.eps))

    # -- algebra ---------------------------------------------------------
    def _check(self, other: "Resource") -> None:
        if other.spec is not self.spec and other.spec != self.spec:
            raise ValueError("resource spec mismatch")

    def add(self, other: "Resource") -> "Resource":
        self._check(other)
        return Resource(self.spec, self.vec + other.vec)

    def sub(self, other: "Resource") -> "Resource":
        """Subtract, requiring `other` ⊑ self (reference Sub asserts too)."""
        self._check(other)
        if not other.less_equal(self):
            raise ValueError(f"cannot subtract {other.as_dict()} from {self.as_dict()}")
        return Resource(self.spec, np.maximum(self.vec - other.vec, 0.0))

    def multi(self, ratio: float) -> "Resource":
        return Resource(self.spec, self.vec * ratio)

    def set_max(self, other: "Resource") -> "Resource":
        """Per-dimension max (reference: SetMaxResource)."""
        self._check(other)
        return Resource(self.spec, np.maximum(self.vec, other.vec))

    def min_dimension(self, other: "Resource") -> "Resource":
        """Per-dimension min (reference: MinDimensionResource)."""
        self._check(other)
        return Resource(self.spec, np.minimum(self.vec, other.vec))

    # -- comparisons -----------------------------------------------------
    def less(self, other: "Resource") -> bool:
        """Strictly less in EVERY dimension (reference: Less)."""
        self._check(other)
        return bool(np.all(self.vec < other.vec))

    def less_equal(self, other: "Resource") -> bool:
        """≤ in every dimension, with per-dim slack (reference: LessEqual).

        A dimension below its negligibility threshold always fits — this
        is what lets a 5-milli-CPU request land on a fully packed node,
        exactly like the reference's minResource handling.
        """
        self._check(other)
        return less_equal_vec(self.vec, other.vec, self.spec.eps)

    def fit_delta(self, other: "Resource") -> "Resource":
        """Per-dimension shortfall of fitting `self` into `other`.

        Positive entries are the unsatisfied amount (reference: FitDelta,
        feeding FitErrors/"why unschedulable" reporting).
        """
        self._check(other)
        return Resource(self.spec, np.maximum(self.vec - other.vec, 0.0))

    def diff(self, other: "Resource") -> tuple["Resource", "Resource"]:
        """(increment, decrement) per dimension (reference: Diff)."""
        self._check(other)
        d = self.vec - other.vec
        return (
            Resource(self.spec, np.maximum(d, 0.0)),
            Resource(self.spec, np.maximum(-d, 0.0)),
        )

    def __repr__(self) -> str:  # pragma: no cover
        parts = ", ".join(f"{n}={v:g}" for n, v in self.as_dict().items() if v)
        return f"Resource({parts or '∅'})"


def less_equal_vec(
    req: np.ndarray, avail: np.ndarray, eps: np.ndarray | float = _FALLBACK_EPS
) -> bool:
    """Vector form of LessEqual, broadcastable; shared with the oracle."""
    req = np.asarray(req)
    avail = np.asarray(avail)
    ok = (req <= avail) | (req < eps)
    return bool(np.all(ok, axis=-1)) if ok.ndim <= 1 else np.all(ok, axis=-1)
