"""Serial CPU oracle for preempt/reclaim: an independent Statement loop.

Reference shape (actions/preempt/preempt.go · Execute, actions/reclaim/
reclaim.go · Execute, framework/statement.go): strictly serial —

    while a starving (preempt) / wanting (reclaim) job exists:
        preemptor = its rank-first pending task
        SCAN candidate nodes; per node:
            open a Statement
            evict candidate victims ONE BY ONE (vetoes recomputed
                against the live state after every eviction)
            the moment the preemptor fits FutureIdle: Commit
                (pipeline it) — scan over, next preemptor
            victims run out first: Discard (roll everything back),
                continue the scan on the next node

Deliberately NumPy + Python loops, sharing NO kernel code with
ops/preemption.py — divergence between the two is a bug in one of them.
The node-scan-with-retry structure is preempt.go's (a discarded
Statement moves on to the next node; only node exhaustion gives up on
the preemptor).  VISIT ORDER is the one deliberate divergence from the
reference: preempt.go walks Go's arbitrary map order; kernel and
oracle both visit fewest-victims-first (lowest index on ties) — a
deterministic tie-break of the same search.  Tier-1 victim vetoes
cover gang minMember survival, conformance criticality, and PDB
floors (all recomputed live, like the kernel's preemptable_mask).

Status ints mirror api.types.TaskStatus: PENDING=0, ALLOCATED=1,
PIPELINED=2, BINDING=3, BOUND=4, RUNNING=5, RELEASING=6, SUCCEEDED=7.
"""

from __future__ import annotations

import numpy as np

from kube_batch_tpu.sim.oracle import _waterfill

PENDING, ALLOCATED, PIPELINED, RELEASING = 0, 1, 2, 6
ALLOCATED_SET = (1, 3, 4, 5)          # Allocated/Binding/Bound/Running
READY_SET = (1, 3, 4, 5, 7)           # + Succeeded
VALID_SET = (0, 1, 2, 3, 4, 5, 7)     # + Pending/Pipelined


class _World:
    """Live mutable view the Statement loop operates on."""

    def __init__(self, snap: dict):
        self.snap = snap
        self.T = snap["task_req"].shape[0]
        self.N = snap["node_idle"].shape[0]
        self.J = snap["job_min"].shape[0]
        self.Q = snap["queue_weight"].shape[0]
        self.task_state = snap["task_state"].astype(np.int64).copy()
        self.task_node = snap["task_node"].astype(np.int64).copy()
        self.future = snap["node_idle"] + snap["node_releasing"]
        self.future = self.future.astype(np.float64).copy()
        self.task_queue = np.array([
            snap["job_queue"][j] if j >= 0 else -1 for j in snap["task_job"]
        ])
        req = np.zeros((self.Q, snap["task_req"].shape[1]))
        for t in range(self.T):
            q = self.task_queue[t]
            if q >= 0:
                req[q] += snap["task_req"][t]
        self.deserved = _waterfill(
            snap["queue_weight"], req, snap["node_cap"].sum(0)
        )

    # -- live accounting ------------------------------------------------
    def counts(self, j: int, statuses) -> int:
        return int(np.sum(
            np.isin(self.task_state, statuses) & (self.snap["task_job"] == j)
        ))

    def queue_alloc(self) -> np.ndarray:
        """f32[Q, R] live held requests (allocated statuses + pipelined)."""
        held = (
            np.isin(self.task_state, ALLOCATED_SET)
            | (self.task_state == PIPELINED)
        ) & (self.snap["task_job"] >= 0)
        alloc = np.zeros_like(self.deserved)
        for t in np.nonzero(held)[0]:
            q = self.task_queue[t]
            if q >= 0:
                alloc[q] += self.snap["task_req"][t]
        return alloc

    def job_alloc(self) -> np.ndarray:
        held = (
            np.isin(self.task_state, ALLOCATED_SET)
            | (self.task_state == PIPELINED)
        ) & (self.snap["task_job"] >= 0)
        alloc = np.zeros((self.J, self.snap["task_req"].shape[1]))
        for t in np.nonzero(held)[0]:
            alloc[self.snap["task_job"][t]] += self.snap["task_req"][t]
        return alloc

    def fits(self, req: np.ndarray, avail: np.ndarray) -> bool:
        eps = self.snap["eps"]
        return bool(np.all((req <= avail) | (req < eps)))


def _job_rank_keys(w: _World):
    """i32[J] dense job ranks with the default tiered keys:
    priority desc > gang-unready-first > drf dominant share asc >
    creation asc (framework/policy.py · job_rank with the default conf).
    """
    snap = w.snap
    total = np.maximum(snap["node_cap"].sum(0), 1e-9)
    jalloc = w.job_alloc()
    keys = []
    for j in range(w.J):
        ready = w.counts(j, READY_SET) + int(np.sum(
            (w.task_state == PIPELINED) & (snap["task_job"] == j)
        ))
        gang_unready = 1.0 if ready >= snap["job_min"][j] else 0.0
        share = float((jalloc[j] / total).max())
        keys.append((
            -snap["job_prio"][j],        # priority plugin (tier 1)
            gang_unready,                # gang plugin (tier 1)
            share,                       # drf plugin (tier 2)
            snap["job_order"][j],        # creation tiebreak
        ))
    order = sorted(range(w.J), key=lambda j: keys[j])
    rank = np.zeros(w.J, np.int64)
    for r, j in enumerate(order):
        rank[j] = r
    return rank


def _task_sort_key(w: _World, t: int, qshare: np.ndarray, jrank: np.ndarray):
    """Serial analog of the policy's task rank: queue share, then the
    job's tiered rank, then task priority desc, then creation."""
    q = w.task_queue[t]
    j = w.snap["task_job"][t]
    return (
        float(qshare[q]) if q >= 0 else 0.0,
        int(jrank[j]) if j >= 0 else 0,
        -w.snap["task_prio"][t],
        w.snap["task_order"][t],
    )


def _queue_share(w: _World) -> np.ndarray:
    alloc = w.queue_alloc()
    with np.errstate(invalid="ignore"):
        ratio = np.where(
            w.deserved > 0, alloc / np.maximum(w.deserved, 1e-9),
            np.where(alloc > 0, 1e9, 0.0),
        )
    return ratio.max(axis=1)


def _gang_veto_ok(w: _World, v: int) -> bool:
    """gang PreemptableFn: victim's job must keep >= minMember ready."""
    j = w.snap["task_job"][v]
    if j < 0:
        return True
    ready = w.counts(j, READY_SET)
    return ready - 1 >= w.snap["job_min"][j]


def _conformance_ok(w: _World, v: int) -> bool:
    return not bool(w.snap["task_critical"][v])


def _stays_above_deserved(w: _World, v: int) -> bool:
    """proportion's reclaim floor, per meaningful dimension."""
    q = w.task_queue[v]
    if q < 0:
        return True
    alloc = w.queue_alloc()[q] - w.snap["task_req"][v]
    d = w.deserved[q]
    beps = w.snap["besteffort_eps"]
    return bool(np.all((d <= alloc) | (d < beps)))


def _pdb_at_floor(w: _World) -> np.ndarray | None:
    """bool[B]: budgets that would be violated by losing one more
    healthy member (pdb plugin, tier 1 — plugins/pdb.py semantics:
    healthy = live allocated members per budget)."""
    pdb_min = w.snap.get("pdb_min")
    if pdb_min is None or len(pdb_min) == 0:
        return None
    healthy = (
        np.isin(w.task_state, ALLOCATED_SET).astype(np.float64)
        @ w.snap["task_pdbs"].astype(np.float64)
    )
    return healthy - 1 < pdb_min


def _candidate_victims(w: _World, p: int, mode: str, jrank, prov: set):
    """Victim candidacy under the LIVE state (recomputed per eviction)."""
    snap = w.snap
    pq, pj = w.task_queue[p], snap["task_job"][p]
    at_floor = _pdb_at_floor(w)
    out = []
    for v in range(w.T):
        if v in prov:
            continue
        if snap["task_state"][v] not in ALLOCATED_SET:
            continue  # must really hold resources on the cluster
        if w.task_state[v] not in ALLOCATED_SET:
            continue  # already victimized this cycle
        if w.task_node[v] < 0 or snap["task_job"][v] < 0:
            continue
        if not _gang_veto_ok(w, v) or not _conformance_ok(w, v):
            continue  # tier-1 veto (decisive tier)
        if at_floor is not None and bool(
            (snap["task_pdbs"][v] * at_floor).sum() > 0
        ):
            continue  # pdb plugin: ALL covering budgets must survive
        if mode == "preempt":
            if w.task_queue[v] != pq:
                continue
            if snap["task_job"][v] == pj:
                continue
            if jrank[snap["task_job"][v]] <= jrank[pj]:
                continue  # only less-deserving jobs
        else:  # reclaim
            if w.task_queue[v] == pq:
                continue
            if not _stays_above_deserved(w, v):
                continue
        out.append(v)
    return out


def _sacrifice_order(w: _World, victims, qshare, jrank):
    """Least deserving evicted first = reverse of the task rank."""
    return sorted(
        victims, key=lambda v: _task_sort_key(w, v, qshare, jrank),
        reverse=True,
    )


def _affinity_row_ok(w: _World, p: int, n: int) -> bool:
    """Node-level inter-pod affinity feasibility of preemptor p on node
    n against the LIVE state (numpy twin of plugins/predicates.py ·
    pod_affinity_row, the kernel's per-step dyn_predicate_row):
    required terms need a resident of n carrying the label (with the
    k8s bootstrap waiver when NO resident anywhere carries it and p
    itself does); p's anti terms forbid matching residents; residents'
    anti terms symmetrically forbid p's own labels.  Future-oriented:
    RELEASING victims are no longer residents — evicting the anchor of
    p's required affinity must fail the plan."""
    snap = w.snap
    aff = snap["task_aff"][p] > 0
    anti = snap["task_anti"][p] > 0
    own = snap["task_podlabels"][p] > 0
    if not (aff.any() or anti.any() or own.any()):
        return True
    live = (
        np.isin(w.task_state, ALLOCATED_SET) | (w.task_state == PIPELINED)
    ) & (w.task_node >= 0)
    K = snap["task_podlabels"].shape[1]
    here = np.zeros(K, bool)       # labels present among n's residents
    here_anti = np.zeros(K, bool)  # anti terms carried by n's residents
    anywhere = np.zeros(K, bool)   # labels present among ANY resident
    for t in np.nonzero(live)[0]:
        labs = snap["task_podlabels"][t] > 0
        anywhere |= labs
        if w.task_node[t] == n:
            here |= labs
            here_anti |= snap["task_anti"][t] > 0
    if not np.all(~aff | here | (own & ~anywhere)):
        return False               # a required term lacks anchor+waiver
    if np.any(anti & here):
        return False               # p's anti term matches a resident
    if np.any(own & here_anti):
        return False               # symmetry: a resident repels p
    return True


def _node_scan_order(w: _World, p: int, victims, qshare, jrank,
                     excluded: set[int]):
    """Candidate nodes for preemptor p, in the order the search visits
    them: fewest victims needed first (in sacrifice order against the
    current state), lowest node index on ties — the deterministic
    tie-break both the kernel and this oracle use where preempt.go
    walks Go's arbitrary map order.  `excluded` nodes (whose Statement
    already failed for p) are skipped — the retry scan."""
    snap = w.snap
    preq = snap["task_req"][p]
    order = _sacrifice_order(w, victims, qshare, jrank)
    ranked: list[tuple[int, int]] = []
    for n in range(w.N):
        if n in excluded or not snap["node_ready"][n]:
            continue
        from kube_batch_tpu.sim.oracle import _predicate_ok

        if not _predicate_ok(snap, p, n):
            continue
        if not _affinity_row_ok(w, p, n):
            continue  # dyn predicate at plan-open (kernel: choose_node)
        if w.fits(preq, w.future[n]):
            k = 0
        else:
            gain = np.zeros_like(preq)
            k = None
            cnt = 0
            for v in order:
                if w.task_node[v] != n:
                    continue
                cnt += 1
                gain = gain + snap["task_req"][v]
                if w.fits(preq, w.future[n] + gain):
                    k = cnt
                    break
            if k is None:
                continue
        ranked.append((k, n))
    ranked.sort()
    return [n for _k, n in ranked]


def serial_preempt(snap: dict, mode: str = "preempt") -> dict:
    """Run the serial Statement loop (preempt or reclaim) over a
    numpy-ified unpadded snapshot (see oracle.snapshot_to_numpy, plus
    `node_releasing`, `job_order`, `task_critical` keys).

    Returns {"pipelined": [(task, node)], "evicted": [task],
    "victims_per_job": {job: count}, "final_state": i64[T]}.
    """
    w = _World(snap)
    tried: set[int] = set()
    pipelined: list[tuple[int, int]] = []
    evicted: list[int] = []
    victims_per_job: dict[int, int] = {}
    besteffort = np.all(snap["task_req"] < snap["besteffort_eps"], axis=1)

    while True:
        jrank = _job_rank_keys(w)
        qshare = _queue_share(w)
        qalloc = w.queue_alloc()

        # -- who may trigger evictions right now ------------------------
        candidates = []
        for t in range(w.T):
            if w.task_state[t] != PENDING or t in tried or besteffort[t]:
                continue
            j = snap["task_job"][t]
            if j < 0:
                continue
            if w.counts(j, VALID_SET) < snap["job_min"][j]:
                continue  # gang invalid
            ready = w.counts(j, READY_SET)
            pipe = ready + int(np.sum(
                (w.task_state == PIPELINED) & (snap["task_job"] == j)
            ))
            pending_cnt = int(np.sum(
                (w.task_state == PENDING) & (snap["task_job"] == j)
            ))
            if pending_cnt == 0:
                continue
            if mode == "preempt":
                # starving: not ready, not pipelined-satisfiable
                if ready >= snap["job_min"][j] or pipe >= snap["job_min"][j]:
                    continue
            else:
                # reclaim: queue must be under its deserved (not overused)
                q = w.task_queue[t]
                d, a = w.deserved[q], qalloc[q]
                beps = snap["besteffort_eps"]
                if np.all((d <= a) | (d < beps)):
                    continue
            candidates.append(t)
        if not candidates:
            break

        p = min(candidates, key=lambda t: _task_sort_key(w, t, qshare, jrank))
        preq = snap["task_req"][p]

        # -- the node scan: try a Statement per candidate node until one
        # commits (≙ preempt.go iterating nodes, first success wins);
        # a failed node is excluded and the scan continues -------------
        committed = False
        excluded: set[int] = set()
        while not committed:
            victims = _candidate_victims(w, p, mode, jrank, set())
            scan = _node_scan_order(w, p, victims, qshare, jrank, excluded)
            if not scan:
                break  # out of nodes: give up on p for this cycle
            n = scan[0]

            # -- the Statement: evict one by one, vetoes recomputed ----
            prov: set[int] = set()
            saved_future = w.future[n].copy()
            while True:
                if not _affinity_row_ok(w, p, n):
                    break  # evicted the anchor: plan no longer legal
                if w.fits(preq, w.future[n]):
                    # Commit: pipeline the preemptor
                    w.task_state[p] = PIPELINED
                    w.task_node[p] = n
                    w.future[n] = w.future[n] - preq
                    for v in prov:
                        victims_per_job[snap["task_job"][v]] = (
                            victims_per_job.get(snap["task_job"][v], 0) + 1
                        )
                        evicted.append(v)
                    pipelined.append((p, n))
                    committed = True
                    break
                vics = [
                    v for v in _candidate_victims(w, p, mode, jrank, prov)
                    if w.task_node[v] == n
                ]
                if not vics:
                    break
                order = _sacrifice_order(w, vics, qshare, jrank)
                v = order[0]
                prov.add(v)
                w.task_state[v] = RELEASING
                w.future[n] = w.future[n] + snap["task_req"][v]
            if not committed:
                # Discard: restore provisional victims + node capacity,
                # exclude this node, retry the next-best one
                for v in prov:
                    w.task_state[v] = snap["task_state"][v]
                w.future[n] = saved_future
                excluded.add(n)
        tried.add(p)

    return {
        "pipelined": pipelined,
        "evicted": sorted(evicted),
        "victims_per_job": victims_per_job,
        "final_state": w.task_state,
    }
