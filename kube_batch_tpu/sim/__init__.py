"""Simulated cluster backend — the framework's e2e test seam.

Reference counterpart: the FakeBinder/FakeEvictor pattern of the
reference's action tests plus its e2e harness (test/e2e/util.go), folded
into one in-process cluster simulator: binds start pods, evictions pass
through a Releasing grace period, and controllers recreate evicted pods —
so gang/preemption/reclaim semantics are exercised end-to-end with no
real cluster.
"""

from kube_batch_tpu.sim.simulator import SimulatedCluster

__all__ = ["SimulatedCluster"]
