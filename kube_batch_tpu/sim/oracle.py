"""Serial CPU oracle: an independent reimplementation of the reference
allocate loop, for differential testing against the TPU auction kernel.

Reference shape (actions/allocate/allocate.go · Execute with the default
plugin set): strictly one task at a time —

    while work remains:
        queue = hungriest non-overused queue   (proportion: alloc/deserved)
        job   = that queue's neediest valid job (drf share, priority, creation)
        task  = that job's next pending task    (priority, creation)
        nodes = predicate-feasible & resource-fitting
        place on the best-scored node (least-requested + balanced + affinities)
        update idle/shares; re-evaluate everything

Gang all-or-nothing is applied at the end exactly like the session's
bind dispatch: jobs that failed to reach minMember contribute no binds.

Deliberately NumPy + Python loops, sharing NO kernel code with
ops/assignment.py — divergence between the two is a bug in one of them.
"""

from __future__ import annotations

import numpy as np


def _predicate_ok(snap, t, n) -> bool:
    """Static predicates for task t on node n (selector/taints/ports)."""
    sel = snap["task_sel"][t]
    if sel.sum() > 0 and (sel * snap["node_labels"][n]).sum() < sel.sum():
        return False
    taints = snap["node_taints"][n]
    if taints.sum() > 0:
        untolerated = taints * (1.0 - snap["task_tol"][t])
        if untolerated.sum() > 0:
            return False
    if (snap["task_ports"][t] * snap["node_ports"][n]).sum() > 0:
        return False
    return bool(snap["node_ready"][n])


def _pod_affinity_ok(snap, t, n, placed_node, resident_labels) -> bool:
    """Inter-pod affinity for t on n given current placements.
    resident_labels[n] = bool[K] labels present among node n's residents;
    the bootstrap waiver applies per term when NO node carries it."""
    aff = snap["task_aff"][t]
    if aff.sum() > 0:
        exists_somewhere = resident_labels.any(axis=0)
        for k in np.nonzero(aff)[0]:
            if resident_labels[n, k]:
                continue
            if not exists_somewhere[k] and snap["task_podlabels"][t, k] > 0:
                continue  # bootstrap
            return False
    anti = snap["task_anti"][t]
    if anti.sum() > 0 and (anti * resident_labels[n]).sum() > 0:
        return False
    # symmetry: residents' anti terms vs t's labels
    if (snap["task_podlabels"][t] * snap["node_anti"][n]).sum() > 0:
        return False
    return True


def serial_allocate(snap) -> dict:
    """Run the serial reference loop over a numpy-ified snapshot.

    `snap` is a dict of numpy arrays with the same keys/shapes as
    SnapshotTensors fields (unpadded).  Returns {"assigned": i32[T] node
    or -1, "bound": bool[T] after the gang gate}.
    """
    T = snap["task_req"].shape[0]
    N = snap["node_idle"].shape[0]
    J = snap["job_min"].shape[0]
    Q = snap["queue_weight"].shape[0]
    R = snap["task_req"].shape[1]
    K = snap["task_podlabels"].shape[1]
    eps = snap["eps"]
    beps = snap["besteffort_eps"]

    idle = snap["node_idle"].copy()
    assigned = np.full(T, -1, np.int32)
    pending = snap["task_state"] == 0  # PENDING
    task_queue = np.array(
        [snap["job_queue"][j] if j >= 0 else -1 for j in snap["task_job"]]
    )

    # residents from the snapshot (already-running pods)
    resident_labels = np.zeros((N, K), bool)
    node_anti = np.zeros((N, K), bool)
    held0 = np.isin(snap["task_state"], (1, 3, 4, 5)) & (snap["task_node"] >= 0)
    for t in np.nonzero(held0)[0]:
        n = snap["task_node"][t]
        resident_labels[n] |= snap["task_podlabels"][t] > 0
        node_anti[n] |= snap["task_anti"][t] > 0
    snap = dict(snap)
    snap["node_anti"] = node_anti

    # queue deserved via the same waterfill contract (independent impl)
    requests = np.zeros((Q, R))
    for t in range(T):
        q = task_queue[t]
        if q >= 0:
            requests[q] += snap["task_req"][t]
    deserved = _waterfill(snap["queue_weight"], requests, snap["node_cap"].sum(0))

    # live per-queue / per-job allocations (include snapshot residents)
    q_alloc = np.zeros((Q, R))
    j_alloc = np.zeros((J, R))
    for t in np.nonzero(held0)[0]:
        q = task_queue[t]
        if q >= 0:
            q_alloc[q] += snap["task_req"][t]
        j = snap["task_job"][t]
        if j >= 0:
            j_alloc[j] += snap["task_req"][t]
    total = np.maximum(snap["node_cap"].sum(0), 1e-9)

    placed_count = np.zeros(J, np.int32)
    besteffort = np.all(snap["task_req"] < beps, axis=1)

    def ready_count(j):
        base = np.sum(
            np.isin(snap["task_state"], (1, 3, 4, 5, 7)) & (snap["task_job"] == j)
        )
        return base + placed_count[j]

    def valid_count(j):
        return np.sum(
            np.isin(snap["task_state"], (0, 1, 2, 3, 4, 5, 7))
            & (snap["task_job"] == j)
        )

    while True:
        # candidate tasks: pending, not best-effort, job valid, queue not overused
        cands = []
        for t in np.nonzero(pending)[0]:
            j = snap["task_job"][t]
            if j < 0 or besteffort[t]:
                continue
            if valid_count(j) < snap["job_min"][j]:
                continue
            q = task_queue[t]
            meaningful = deserved[q] >= beps
            if np.all(~meaningful | (deserved[q] <= q_alloc[q])):
                continue  # overused
            cands.append(t)
        if not cands:
            break

        def rank_key(t):
            j = snap["task_job"][t]
            q = task_queue[t]
            d = np.where(deserved[q] > 0, q_alloc[q] / np.maximum(deserved[q], 1e-9),
                         np.where(q_alloc[q] > 0, 1e9, 0.0))
            qshare = d.max()
            jshare = (j_alloc[j] / total).max()
            gang_unready = 0.0 if ready_count(j) < snap["job_min"][j] else 1.0
            return (
                qshare,
                snap["job_prio"][j] * -1.0,
                gang_unready,
                jshare,
                -snap["task_prio"][t],
                snap["task_order"][t],
            )

        t = min(cands, key=rank_key)
        r = snap["task_req"][t]
        best_n, best_score = -1, -np.inf
        for n in range(N):
            if not np.all((r <= idle[n]) | (r < eps)):
                continue
            if not _predicate_ok(snap, t, n):
                continue
            if not _pod_affinity_ok(snap, t, n, assigned, resident_labels):
                continue
            cap = np.maximum(snap["node_cap"][n], 1e-9)
            frac = np.clip(idle[n] - r, 0, None) / cap
            w = (r > 0).astype(float)
            least = (frac * w).sum() / max(w.sum(), 1.0) * 10.0
            used_after = (snap["node_cap"][n] - idle[n]) + r
            fr = np.clip(used_after / cap, 0, 1)
            bal = (1.0 - abs(fr[0] - fr[1])) * 10.0
            score = least + bal
            if score > best_score + 1e-12:
                best_n, best_score = n, score
        if best_n < 0:
            pending[t] = False  # unschedulable now; park it
            continue

        assigned[t] = best_n
        pending[t] = False
        idle[best_n] -= r
        q = task_queue[t]
        q_alloc[q] += r
        j_alloc[snap["task_job"][t]] += r
        placed_count[snap["task_job"][t]] += 1
        resident_labels[best_n] |= snap["task_podlabels"][t] > 0
        node_anti[best_n] |= snap["task_anti"][t] > 0

    # gang gate at dispatch
    bound = np.zeros(T, bool)
    for t in np.nonzero(assigned >= 0)[0]:
        j = snap["task_job"][t]
        if ready_count(j) >= snap["job_min"][j]:
            bound[t] = True
    return {"assigned": assigned, "bound": bound}


def _waterfill(weights, requests, cap):
    Q, R = requests.shape
    deserved = np.zeros_like(requests)
    remaining = cap.astype(float).copy()
    unsat = np.ones_like(requests, bool)
    for _ in range(Q + 1):
        w = np.where(unsat, weights[:, None], 0.0)
        wsum = w.sum(axis=0)
        inc = np.where(wsum > 0, remaining[None, :] * w / np.maximum(wsum, 1e-9), 0.0)
        filled = deserved + inc
        hit = filled >= requests
        filled = np.minimum(filled, requests)
        remaining = np.maximum(remaining - (filled - deserved).sum(axis=0), 0.0)
        deserved, unsat = filled, unsat & ~hit
    return deserved


def snapshot_to_numpy(snap, meta) -> dict:
    """SnapshotTensors → unpadded numpy dict for the oracle."""
    Tn = meta.num_real_tasks
    Nn = meta.num_real_nodes
    out = {}
    for name in (
        "task_req", "task_state", "task_job", "task_node", "task_prio",
        "task_order", "task_sel", "task_tol", "task_ports",
        "task_podlabels", "task_aff", "task_anti", "task_critical",
    ):
        out[name] = np.asarray(getattr(snap, name))[:Tn]
    for name in ("node_cap", "node_idle", "node_releasing", "node_labels",
                 "node_taints", "node_ports", "node_ready"):
        out[name] = np.asarray(getattr(snap, name))[:Nn]
    for name in ("job_queue", "job_min", "job_prio", "job_order"):
        out[name] = np.asarray(getattr(snap, name))[: len(meta.job_names)]
    out["queue_weight"] = np.asarray(snap.queue_weight)[: len(meta.queue_names)]
    out["task_pdbs"] = np.asarray(snap.task_pdbs)[:Tn]
    out["pdb_min"] = np.asarray(snap.pdb_min)
    out["eps"] = np.asarray(snap.eps)
    out["besteffort_eps"] = np.asarray(snap.besteffort_eps)
    return out
