"""In-process cluster simulator implementing the backend seam.

The simulator plays the roles that sit across the API boundary from the
reference scheduler: kubelet (starting bound pods), the API server
(deleting evicted pods) and workload controllers (recreating deleted
pods).  Time is discrete: effects of binds/evicts land at the next
`tick()`, which creates the same in-flight windows (BINDING, RELEASING)
the reference sees from asynchronous cluster round-trips — exercising
FutureIdle accounting and pipelined placements.
"""

from __future__ import annotations

from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.cache.cluster import Node, Pod, PodGroup, Queue


class SimulatedCluster:
    """Implements Binder/Evictor/StatusUpdater against a SchedulerCache."""

    def __init__(self) -> None:
        self.cache: SchedulerCache | None = None
        self.binds: list[tuple[str, str]] = []
        self.evictions: list[tuple[str, str]] = []
        self.status_updates: list[PodGroup] = []
        self._starting: list[str] = []   # pod uids bound, not yet running
        self._deleting: list[str] = []   # pod uids evicted, not yet recreated

    # -- backend seam ---------------------------------------------------
    def bind(self, pod: Pod, node_name: str) -> None:
        self.binds.append((pod.name, node_name))
        self._starting.append(pod.uid)

    def evict(self, pod: Pod, reason: str) -> None:
        self.evictions.append((pod.name, reason))
        self._deleting.append(pod.uid)

    def update_pod_group(self, group: PodGroup) -> None:
        self.status_updates.append(group)

    # -- world-building -------------------------------------------------
    def attach(self, cache: SchedulerCache) -> None:
        self.cache = cache

    def add_node(self, node: Node) -> None:
        self.cache.add_node(node)

    def delete_node(self, name: str) -> None:
        """Node vanishes (power loss / cordoned away): the cache
        unplaces its residents, which re-enter Pending for rescheduling
        — same semantics as ExternalCluster.delete_node, so a chaos
        trace replays identically against either backend."""
        self.cache.delete_node(name)

    def delete_pod(self, uid: str) -> None:
        """Remove a pod for good (controller reaping a finished
        workload) — unlike evict, nothing recreates it."""
        self.cache.delete_pod(uid)

    def delete_pod_group(self, name: str) -> None:
        self.cache.delete_pod_group(name)

    def submit(self, group: PodGroup, pods: list[Pod]) -> None:
        """One job arriving: PodGroup object plus its member pods."""
        self.cache.add_pod_group(group)
        for pod in pods:
            pod.group = group.name
            self.cache.add_pod(pod)

    def submit_to_group(self, group_name: str, pods: list[Pod]) -> None:
        """Additional member pods for an existing PodGroup (scale-up)."""
        for pod in pods:
            pod.group = group_name
            self.cache.add_pod(pod)

    def add_queue(self, queue: Queue) -> None:
        self.cache.add_queue(queue)

    def add_claim(self, claim) -> None:
        self.cache.add_claim(claim)

    def add_storage_class(self, sc) -> None:
        self.cache.add_storage_class(sc)

    def add_namespace(self, ns) -> None:
        self.cache.add_namespace(ns)

    def add_pdb(self, pdb) -> None:
        self.cache.add_pdb(pdb)

    # -- time -----------------------------------------------------------
    def tick(self) -> None:
        """Land in-flight effects: bound pods start running; evicted pods
        are deleted and recreated as fresh Pending pods (controller
        behavior), freeing their nodes."""
        starting, self._starting = self._starting, []
        for uid in starting:
            if uid in self.cache._pods:
                self.cache.update_pod_status(uid, TaskStatus.RUNNING)
        deleting, self._deleting = self._deleting, []
        for uid in deleting:
            pod = self.cache._pods.get(uid)
            if pod is None:
                continue
            template = pod.respawn()
            self.cache.delete_pod(uid)
            self.cache.add_pod(template)


def make_world(
    spec, default_queue: str = "default"
) -> tuple[SchedulerCache, SimulatedCluster]:
    """Wire a fresh cache to a fresh simulator."""
    sim = SimulatedCluster()
    cache = SchedulerCache(
        spec=spec,
        binder=sim,
        evictor=sim,
        status_updater=sim,
        default_queue=default_queue,
    )
    sim.attach(cache)
    return cache, sim
