"""Why-is-my-pod-not-scheduled diagnosis.

Reference counterpart: pkg/scheduler/api/unschedule_info.go — the
`FitErrors` aggregation that collects per-node predicate failures per
task and renders the familiar "0/4 nodes are available: 3 Insufficient
cpu, 1 node(s) had taints" events users debug with.

TPU-native shape: the per-(task, node) failure matrix already exists on
device — it is the complement of the predicate mask and the resource-fit
matrix the allocate auction computed.  Diagnosis is therefore a handful
of whole-snapshot reductions (one [T, N] pass per failure class), pulled
to host once per cycle only for tasks that stayed Pending.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from kube_batch_tpu.api.snapshot import SnapshotTensors, fits
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.ops.assignment import AllocState

#: Per-cycle cap on rendered unschedulable events (diagnose_pending's
#: default) — the single source of truth failure_counts_subset validates
#: its window against: every consumed row must sit inside the gathered
#: [P, N] subset, so the consumer's event cap must stay BELOW max_rows.
MAX_DIAG_EVENTS = 1000


def failure_counts(
    snap: SnapshotTensors,
    state: AllocState,
    predicate_mask: jnp.ndarray,
) -> dict[str, jnp.ndarray]:
    """Per-task failure tallies over real nodes (device-side).

    Returns i32[T] arrays: nodes total, predicate-vetoed nodes, nodes
    left short on each resource dimension (i32[T, R]), and nodes fully
    fitting (should be 0 for a still-pending task — nonzero means the
    task lost the auction to rank order, e.g. queue over fair share).
    """
    node_ok = snap.node_mask & snap.node_ready
    fit = fits(
        snap.task_req[:, None, :], state.node_idle[None, :, :], snap.eps
    )

    pred_fail = (~predicate_mask) & node_ok[None, :]
    unfit = predicate_mask & ~fit & node_ok[None, :]
    feasible = predicate_mask & fit & node_ok[None, :]
    # Per-dimension shortfalls, one [T, N] pass per resource dim: a full
    # [T, N, R] mask would be R× the predicate matrix's footprint, which
    # at 50k-pod/5k-node scale is gigabytes; R is small, N is not.
    insufficient = jnp.stack(
        [
            jnp.sum(
                unfit
                & (snap.task_req[:, None, r] > state.node_idle[None, :, r])
                & (snap.task_req[:, r] >= snap.eps[r])[:, None],
                axis=1,
            )
            for r in range(snap.num_resources)
        ],
        axis=1,
    ).astype(jnp.int32)                                    # i32[T, R]
    return {
        "nodes": jnp.sum(node_ok).astype(jnp.int32),
        "predicate_failed": jnp.sum(pred_fail, axis=1).astype(jnp.int32),
        "insufficient": insufficient,
        "feasible": jnp.sum(feasible, axis=1).astype(jnp.int32),
    }


def diag_window_rows(max_events: int | None) -> int:
    """The gathered-window size a caller should pass for a given
    consumer event cap: derived, not hand-picked, so raising
    MAX_DIAG_EVENTS can never silently outgrow the window (the
    ADVICE-round-5 cross-module invariant, enforced by derivation
    instead of prose).  2x headroom keeps the window comfortably
    above the cap while staying a power-of-two-ish bucket."""
    if max_events is None:
        return 2048
    return max(2048, 2 * int(max_events))


def failure_counts_subset(
    snap: SnapshotTensors,
    state: AllocState,
    policy,
    max_rows: int | None = None,
    max_events: int | None = MAX_DIAG_EVENTS,
) -> dict[str, jnp.ndarray]:
    """failure_counts restricted to the (bounded) pending set, scattered
    back to [T] — the active-set diagnosis.

    Every [T, N] tally pass shrinks to [P, N] (P = min(max_rows, T)): at
    flagship 65k×8k shapes the full-diagnosis term is a measured 83 ms
    per cycle; the P=2048 projection is ~1/32 of that data.  Exactness:
    only PENDING rows of the result are ever consumed
    (diagnose_pending), jnp.nonzero gathers pending indices in
    ascending order — the same order diagnose_pending walks — and its
    event volume is capped at max_events=1000 < P, so every consumed
    row is inside the gathered set.  Rows beyond P (backlogs deeper
    than P pending) scatter back as zeros and are only ever summarized
    by the "... and N more" tail line.  Dynamic predicates evaluate
    through their subset seam (residents from the FULL state, candidate
    rows from the gathered subset); for a policy carrying a dynamic
    predicate WITHOUT a subset variant this function falls back to the
    exact full-[T, N] failure_counts internally.

    Purely data-flow (gather/compute/scatter, no lax.cond): shape-
    preserving control flow is what trips the XLA:TPU compile cliff
    (BASELINE.md round-5 negative result); gathers do not.

    `max_events` is the CONSUMER's per-cycle event cap (diagnose_pending
    walks at most that many pending rows): the exactness argument above
    requires it to stay below `max_rows`, enforced in code instead of
    prose — `max_rows` now DEFAULTS to `diag_window_rows(max_events)`
    (derived from the cap, so a caller that only raises its event cap
    can never silently outgrow the window), and an explicitly-passed
    window that violates the invariant raises — shrinking `max_rows`
    below the cap would silently scatter consumed rows back as
    all-zero tallies, rendering as misleading "0/N nodes available:"
    events with no reasons.  A caller that consumes rows by its own
    window rule (tests probing small windows, benchmarks) opts out
    with `max_events=None`.
    """
    if max_rows is None:
        max_rows = diag_window_rows(max_events)
    if max_events is not None and max_events >= max_rows:
        raise ValueError(
            f"failure_counts_subset: max_events={max_events} must stay "
            f"below max_rows={max_rows} — pending rows beyond the "
            "gathered window scatter back as all-zero tallies and would "
            "render as '0/N nodes available:' events with no reasons"
        )
    from kube_batch_tpu.cache.packer import gather_tasks

    if not policy.has_subset_dynamic_predicates:
        # A registered dynamic predicate with no subset variant cannot
        # be evaluated for the gathered rows — silently dropping it
        # would report its vetoed nodes as "feasible".  Fall back to
        # the exact full-[T, N] evaluation instead of mis-diagnosing
        # (checked before any gather work, which the fallback discards).
        mask = policy.predicate_mask(snap)
        dyn = policy.dynamic_predicate_fn(snap, state, immediate=True)
        return failure_counts(
            snap, state, mask if dyn is None else mask & dyn
        )

    T = snap.num_tasks
    P = min(max_rows, T)
    pending = (
        (state.task_state == int(TaskStatus.PENDING)) & snap.task_mask
    )
    n_pend = jnp.sum(pending)
    idx = jnp.nonzero(pending, size=P, fill_value=0)[0]        # i32[P], asc
    valid = jnp.arange(P) < n_pend
    sub = gather_tasks(snap, idx, valid)
    sub_state = state.replace(
        task_state=state.task_state[idx],
        task_node=state.task_node[idx],
    )
    mask = policy.predicate_mask(sub)
    dyn = policy.dynamic_predicate_subset_fn(
        snap, state, sub, sub_state, immediate=True
    )
    counts = failure_counts(sub, sub_state, mask if dyn is None else mask & dyn)
    vz = valid.astype(jnp.int32)
    return {
        "nodes": counts["nodes"],
        "predicate_failed": jnp.zeros(T, jnp.int32)
        .at[idx].max(counts["predicate_failed"] * vz),
        "insufficient": jnp.zeros((T, snap.num_resources), jnp.int32)
        .at[idx].max(counts["insufficient"] * vz[:, None]),
        "feasible": jnp.zeros(T, jnp.int32)
        .at[idx].max(counts["feasible"] * vz),
    }


def render_fit_error(
    task_name: str,
    counts: dict[str, np.ndarray],
    t: int,
    resource_names: tuple[str, ...],
) -> str:
    """One event line per unschedulable task (≙ FitErrors.Error())."""
    total = int(counts["nodes"])
    reasons: list[str] = []
    pf = int(counts["predicate_failed"][t])
    if pf:
        reasons.append(f"{pf} node(s) failed predicates")
    insuff = counts["insufficient"][t]
    for r, name in enumerate(resource_names):
        c = int(insuff[r])
        if c:
            reasons.append(f"{c} Insufficient {name}")
    feas = int(counts["feasible"][t])
    if feas:
        reasons.append(
            f"{feas} node(s) feasible but outranked (fair share / gang order)"
        )
    if not reasons:
        reasons.append("no nodes in cluster")
    return f"0/{total} nodes are available for {task_name}: " + ", ".join(reasons)


def diagnose_pending(
    ssn, max_events: int = MAX_DIAG_EVENTS
) -> list[tuple[str, str, str]]:
    """(pod name, namespace, message) triples for real tasks still
    Pending at session end — the caller attaches each to its pod as a
    structured event.

    Called from close_session; the [T, N] reductions run once on device,
    only the small per-task tallies cross to host.  `max_events` bounds
    per-cycle event volume on huge backlogs (the tail repeats the same
    few reasons anyway).
    """
    snap, state = ssn.snap, ssn.state
    task_state = ssn.host_task_state()
    pending = np.nonzero(
        task_state[: ssn.meta.num_real_tasks] == int(TaskStatus.PENDING)
    )[0]
    if pending.size == 0:
        return []
    # The fused cycle precomputes the tallies inside ITS dispatch
    # (actions/fused.py) — compiling a separate diagnosis program here
    # would be a second large in-process compile, which hangs the
    # tunneled backend at flagship shapes.  Only the per-action
    # fallback path (custom actions, small worlds) jits its own.
    if ssn._diag is not None:
        # ONE batched D2H for all per-reason tallies: any cycle with a
        # pending backlog pays this fetch, and per-array np.asarray
        # reads cost a tunnel round trip EACH (~68 ms × ~8 reasons —
        # a large unattributed host term on oversubscribed steady
        # state).
        counts = jax.device_get(dict(ssn._diag))
    else:
        policy = ssn.policy
        diag = getattr(policy, "_diagnose_jit", None)
        if diag is None:
            def full_mask(s, st):
                m = policy.predicate_mask(s)
                # immediate=True: diagnose against the same mask the
                # Idle pass refused with (incl. anti-affinity vs
                # RELEASING residents), so "why pending" matches the
                # actual refusal.
                dyn = policy.dynamic_predicate_fn(s, st, immediate=True)
                return m if dyn is None else m & dyn

            diag = jax.jit(
                lambda s, st: failure_counts(s, st, full_mask(s, st))
            )
            policy._diagnose_jit = diag
        counts = jax.device_get(dict(diag(snap, state)))
    out: list[tuple[str, str, str]] = []
    # Decision records (kube_batch_tpu/trace/): the rendered fit-error
    # reasons ARE each pending pod's "refused" story entry — the
    # /debug/pods/<uid> answer reuses this diagnosis pass verbatim
    # instead of compiling a second device program.
    from kube_batch_tpu import trace

    dlog = trace.decision_log()
    cyc = trace.current_cycle()
    for t in pending[:max_events]:
        pod = ssn.meta.task_pods[t]
        message = render_fit_error(
            pod.name, counts, t, ssn.meta.spec.names
        )
        out.append((pod.name, pod.namespace, message))
        if dlog is not None:
            dlog.note_pod(
                pod.uid, "refused", cyc,
                name=pod.name, namespace=pod.namespace, group=pod.group,
                reasons=message,
            )
    if pending.size > max_events:
        out.append((
            "", "default",
            f"... and {pending.size - max_events} more unschedulable tasks",
        ))
    return out
