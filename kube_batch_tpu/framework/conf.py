"""Scheduler policy configuration (`scheduler.conf`).

Reference counterpart: the YAML the reference re-reads every cycle
(pkg/scheduler/scheduler.go · loadSchedulerConf) with `actions:` (a
comma-separated string) and `tiers:` of plugins, plus per-plugin
Arguments and enable flags; default in pkg/scheduler/util.go ·
defaultSchedulerConf.

Same file format here:

    actions: "allocate, backfill"
    tiers:
    - plugins:
      - name: priority
      - name: gang
      - name: conformance
    - plugins:
      - name: drf
      - name: predicates
      - name: proportion
      - name: nodeorder
        arguments:
          nodeorder.leastrequested.weight: 1
"""

from __future__ import annotations

import dataclasses
from typing import Any

import yaml


@dataclasses.dataclass(frozen=True)
class PluginConf:
    """≙ conf.PluginOption: name + Arguments + per-extension enables."""

    name: str
    arguments: tuple[tuple[str, Any], ...] = ()
    enabled: tuple[tuple[str, bool], ...] = ()  # e.g. ("jobOrder", False)

    @property
    def args_dict(self) -> dict[str, Any]:
        return dict(self.arguments)

    def enabled_for(self, point: str) -> bool:
        return dict(self.enabled).get(point, True)


@dataclasses.dataclass(frozen=True)
class TierConf:
    plugins: tuple[PluginConf, ...]


@dataclasses.dataclass(frozen=True)
class SchedulerConf:
    actions: tuple[str, ...]
    tiers: tuple[TierConf, ...]
    #: Top-level arguments (action-scoped knobs, e.g.
    #: `allocate.max_rounds`) — the action analog of per-plugin
    #: Arguments.  The reference has no per-action config; this exists
    #: for the one knob the tensor design adds: the auction round cap,
    #: an operator latency valve (see actions/allocate.py).
    arguments: tuple[tuple[str, Any], ...] = ()

    @property
    def args_dict(self) -> dict[str, Any]:
        return dict(self.arguments)

    @property
    def fingerprint(self) -> int:
        """Stable identity for compiled-policy caching."""
        return hash(self)


def default_conf() -> SchedulerConf:
    """≙ pkg/scheduler/util.go · defaultSchedulerConf: actions
    "allocate, backfill"; tiers [priority, gang, conformance] /
    [drf, predicates, proportion, nodeorder].

    Only plugins/actions actually registered are included, so the default
    path always runs (the full reference set fills in as plugins land).
    """
    from kube_batch_tpu.framework.plugin import (
        ACTION_REGISTRY,
        PLUGIN_REGISTRY,
        ensure_registered,
    )

    ensure_registered()

    tier1 = ("priority", "gang", "conformance", "pdb")
    tier2 = ("drf", "predicates", "proportion", "nodeorder")
    actions = tuple(
        a for a in ("allocate", "backfill") if a in ACTION_REGISTRY
    ) or ("allocate",)
    return SchedulerConf(
        actions=actions,
        tiers=(
            TierConf(
                plugins=tuple(PluginConf(n) for n in tier1 if n in PLUGIN_REGISTRY)
            ),
            TierConf(
                plugins=tuple(PluginConf(n) for n in tier2 if n in PLUGIN_REGISTRY)
            ),
        ),
    )


def parse_conf(text: str) -> SchedulerConf:
    """Parse the scheduler.conf YAML (hot-reload friendly: pure text in,
    immutable conf out)."""
    raw = yaml.safe_load(text)
    if not raw:
        return default_conf()
    raw_actions = raw.get("actions", "allocate, backfill")
    if isinstance(raw_actions, str):
        actions = tuple(a.strip() for a in raw_actions.split(",") if a.strip())
    else:  # YAML list form: actions: [allocate, backfill]
        actions = tuple(str(a).strip() for a in raw_actions)
    tiers: list[TierConf] = []
    for tier_raw in raw.get("tiers", []) or []:
        plugins: list[PluginConf] = []
        for p in tier_raw.get("plugins", []) or []:
            enables = tuple(
                (k[len("enable"):][0].lower() + k[len("enable") + 1:], bool(v))
                for k, v in p.items()
                if k.startswith("enable") and len(k) > len("enable")
            )
            plugins.append(
                PluginConf(
                    name=p["name"],
                    arguments=tuple(sorted((p.get("arguments") or {}).items())),
                    enabled=enables,
                )
            )
        tiers.append(TierConf(plugins=tuple(plugins)))
    arguments = tuple(sorted((raw.get("arguments") or {}).items()))
    if not tiers:
        return dataclasses.replace(
            default_conf(), actions=actions, arguments=arguments
        )
    return SchedulerConf(
        actions=actions, tiers=tuple(tiers), arguments=arguments
    )


def load_conf(path: str | None) -> SchedulerConf:
    """Read + parse a conf file; missing path → defaults (≙ the
    reference's fallback to defaultSchedulerConf)."""
    if path is None:
        return default_conf()
    try:
        with open(path, "r", encoding="utf-8") as f:
            return parse_conf(f.read())
    except FileNotFoundError:
        return default_conf()
