"""Asynchronous wire-commit pipeline: overlap commit RTTs with the
next cycle's solve.

In wire mode the steady-state cycle is dominated by the COMMIT tail:
`close_session` used to block on every bind round trip (~68 ms RTT
each through the tunnel), then the PodGroup status refresh, then the
event sink, before the next cycle could pack.  The reference scheduler
never waits like that — its bind goroutines return before the
apiserver acks (cache.go · Bind) — and our cache already supports the
same structurally: `cache.begin_bind` marks BINDING under the lock
BEFORE the wire call, and failures funnel into the resync queue.  This
module is the missing piece: the wall-clock cycle ends when the cache
mutations land, and the wire RTTs of cycle N flush on worker threads
while cycle N+1 packs and solves.

Semantics:

* **Per-key FIFO ordering.**  Every op carries an ordering key (a
  pod's bind flush keys on ``pod:<uid>``, a PodGroup status write on
  ``group:<name>``, event-sink forwards on ``events``).  Ops sharing a
  key execute strictly in submission order, at most one in flight —
  so a pod's BINDING → wire-bind → rollback/ack sequence can never
  reorder on the wire — while unrelated keys flush concurrently
  across the worker pool.

* **Bounded, with backpressure.**  At most ``max_inflight`` ops may be
  queued+running; a `submit` past the bound BLOCKS the caller (the
  scheduler's commit enqueue — so the solve pauses instead of the
  queue growing without bound).  Submissions from a flush worker
  itself (e.g. the Bound event a bind ack records) bypass the wait:
  blocking a worker on the queue it drains would deadlock the pool.

* **Failure semantics are the cache's.**  The flushed callables are
  the cache's own funnels (`finish_bind`, `_send_job_status`,
  `_send_event`), which already classify transport vs app errors,
  roll back to PENDING + resync on a failed bind, mark
  `_status_retry` on a swallowed status write, and observe
  `task_scheduling_latency` at the wire ack.  An op that still raises
  is a bug: logged with stack, counted in ``flush_errors``, and the
  worker survives.

* **Breaker interplay.**  The guardrail breaker/backoff wraps the
  backend the flushed funnels call, so retry + trip accounting happen
  on the flush side.  When the breaker trips open, queued ops fail
  fast (`BreakerOpen` never touches the wire) and drain into the
  resync queue; the scheduler's quiesced-skip path and
  `Guardrails.pre_cycle` then `drain()` the remainder, so an open
  breaker means ZERO in-flight wire writes — the chaos invariant.

* **Drain on every exit path.**  `drain()` blocks until the queue is
  empty (quiesce/relist in `client.adapter.resume_session`, the
  scheduler loop's exit, the chaos engine's per-tick barrier);
  `close()` drains then stops the workers, and is also registered
  atexit with the same bounded-join discipline as the growth-compile
  threads and the bind fan-out pool — no flush thread may race
  interpreter teardown.  A closed pipeline degrades to synchronous
  inline execution, never drops a commit.

Batch accounting: `begin_cycle()` seals the previous cycle's ops into
a batch; when a sealed batch's last op completes, its flush latency
(first enqueue → last completion) is reported through ``on_flush`` —
the guardrail facade feeds it to a SECOND watchdog, so a slow wire
degrades the ladder even though cycles now return fast.  Per-op
latencies land in ``commit_flush_latency_seconds``; ``cycle_overlap_
ratio`` tracks the fraction of flush busy-time hidden behind in-cycle
compute.

Design doc: doc/design/pipelined-commit.md.
"""

from __future__ import annotations

import atexit
import collections
import logging
import threading
import time

from kube_batch_tpu import metrics, trace
from kube_batch_tpu.trace import context as trace_context

log = logging.getLogger(__name__)

#: Default bound on queued+running ops (--commit-inflight-max).  Sized
#: for a large gang commit (hundreds of binds) without letting a dead
#: wire accumulate an unbounded backlog: past this, the enqueue (and
#: therefore the next solve) waits.
DEFAULT_INFLIGHT_MAX = 256
#: Flush fan-out width — matches Session.BIND_WORKERS (the reference's
#: 16-worker bind pools): each op through a wire backend is a full
#: round trip, and unrelated keys should overlap theirs.
DEFAULT_WORKERS = 16

_worker_tls = threading.local()


class _Op:
    __slots__ = ("key", "verb", "fn", "enqueued_at", "batch",
                 "trace_cycle", "trace_ctx")

    def __init__(self, key, verb, fn, enqueued_at, batch, trace_cycle=0,
                 trace_ctx=None):
        self.key = key
        self.verb = verb
        self.fn = fn
        self.enqueued_at = enqueued_at
        self.batch = batch
        # The scheduler cycle that ENQUEUED this op: flush spans are
        # attributed to it (not to whatever cycle is running when the
        # worker finally lands the RTT), so a Perfetto view shows
        # cycle N's commit tail overlapping cycle N+1's solve.
        self.trace_cycle = trace_cycle
        # The FLOW context active at enqueue (the cycle's trace id):
        # the worker re-binds it around the flush so the wire write
        # carries the enqueuing cycle's traceparent even though it
        # lands threads and cycles later.
        self.trace_ctx = trace_ctx


class CommitPipeline:
    """Bounded in-flight commit queue with per-key ordering.

    One instance per wire-mode daemon, shared by the cache (which
    routes bind/status/event flushes through it when its ``commit``
    attribute is set) and the scheduler loop (cycle batching, overlap
    accounting, drain on quiesce).
    """

    def __init__(
        self,
        cache=None,
        max_inflight: int = DEFAULT_INFLIGHT_MAX,
        workers: int = DEFAULT_WORKERS,
        name: str = "commit",
        on_flush=None,
        trace_scope: str | None = None,
    ) -> None:
        self._cache = cache
        #: Observability scope the flush workers bind at thread start
        #: (kube_batch_tpu/scope.py): a multi-scheduler process routes
        #: each pipeline's spans/transitions to its OWNING scheduler's
        #: tracer instead of interleaving them.
        self._trace_scope = trace_scope
        self.max_inflight = max(int(max_inflight), 1)
        self._nworkers = max(int(workers), 1)
        self.name = name
        self._on_flush = on_flush
        self._cv = threading.Condition()
        self._queues: dict[str, collections.deque] = {}   # key -> FIFO
        self._ready: collections.deque[str] = collections.deque()
        self._running_keys: dict[str, int] = {}
        self._pending = 0            # submitted, not yet completed
        self._closed = False
        self._threads: list[threading.Thread] = []
        # -- cycle batches (flush-latency attribution) ------------------
        self._batch_seq = 0
        self._batches: dict[int, dict] = {
            0: {"pending": 0, "first": None, "last": None, "sealed": False}
        }
        self.batches_completed = 0
        # -- stats (chaos invariants + observability) -------------------
        self.max_depth_seen = 0
        #: Two ops of one key observed running concurrently — the
        #: per-pod wire-order guarantee broken.  Structurally
        #: impossible; counted so the chaos engine can ASSERT it.
        self.order_violations = 0
        self.flush_errors = 0
        self.backpressure_waits = 0
        self._flush_busy_s = 0.0
        self._overlap_busy_s = 0.0
        self._solving = False
        # Same teardown discipline as the growth-compile threads and
        # the bind fan-out pool: a flush thread alive at interpreter
        # teardown must not race the dying runtime.
        atexit.register(self._atexit_close)

    # -- submission seams ------------------------------------------------
    def submit_bind(self, pod_uid: str, node_name: str) -> None:
        """Flush one bind's wire round trip (the cache already marked
        the pod BINDING on the cycle thread via `begin_bind`)."""
        cache = self._cache
        self.submit(
            f"pod:{pod_uid}",
            lambda: cache.finish_bind(pod_uid, node_name),
            verb="bind",
        )

    def submit(self, key: str, fn, verb: str = "write"):
        """Enqueue one flush op under `key`.  Blocks while the queue is
        at ``max_inflight`` (backpressure — unless called FROM a flush
        worker, which must never wait on its own pool).  On a closed
        pipeline the op runs inline, synchronously: shutdown degrades
        to the sync commit path, never to a dropped write."""
        in_worker = getattr(_worker_tls, "active", False)
        with self._cv:
            blocked = False
            while (
                not self._closed
                and not in_worker
                and self._pending >= self.max_inflight
            ):
                if not blocked:
                    blocked = True
                    self.backpressure_waits += 1
                    metrics.commit_backpressure_waits.inc()
                self._cv.wait()
            if self._closed:
                run_inline = True
            else:
                run_inline = False
                now = time.monotonic()
                b = self._batches[self._batch_seq]
                if b["first"] is None:
                    b["first"] = now
                b["pending"] += 1
                op = _Op(key, verb, fn, now, self._batch_seq,
                         trace.current_cycle(), trace_context.current())
                q = self._queues.get(key)
                if q is None:
                    q = self._queues[key] = collections.deque()
                    self._running_keys.setdefault(key, 0)
                q.append(op)
                if self._running_keys[key] == 0 and len(q) == 1:
                    self._ready.append(key)
                self._pending += 1
                self.max_depth_seen = max(self.max_depth_seen, self._pending)
                metrics.set_commit_queue_depth(self._pending)
                if len(self._threads) < self._nworkers:
                    self._spawn_workers_locked()
                self._cv.notify()
        if run_inline:
            return fn()
        return None

    def _spawn_workers_locked(self) -> None:
        while len(self._threads) < self._nworkers:
            t = threading.Thread(
                target=self._worker,
                name=f"commit-flush-{len(self._threads)}",
                daemon=True,
            )
            self._threads.append(t)
            t.start()

    # -- the flush loop --------------------------------------------------
    def _worker(self) -> None:
        _worker_tls.active = True
        if self._trace_scope is not None:
            from kube_batch_tpu import scope

            scope.bind(self._trace_scope)
        while True:
            with self._cv:
                while not self._ready:
                    if self._closed and self._pending == 0:
                        return
                    self._cv.wait(0.1 if self._closed else None)
                key = self._ready.popleft()
                op = self._queues[key].popleft()
                self._running_keys[key] += 1
                if self._running_keys[key] > 1:  # pragma: no cover —
                    # structurally impossible; counted for the chaos
                    # engine's commit-order assertion.
                    self.order_violations += 1
            started = time.monotonic()
            overlapped = self._solving
            flush_ok = True
            # Re-bind the enqueuing cycle's flow context: the flush's
            # wire write (and its span) stitches to the cycle that
            # decided it, not whatever cycle is solving right now.
            tok = trace_context.bind(op.trace_ctx) \
                if op.trace_ctx is not None else None
            try:
                with trace.span(
                    "flush:" + op.verb, cycle=op.trace_cycle,
                    key=op.key,
                ):
                    op.fn()
            except Exception:  # noqa: BLE001 — the flushed funnels own
                # their failure semantics (rollback/resync/_status_retry);
                # anything escaping is a bug, but the worker must survive.
                flush_ok = False
                self.flush_errors += 1
                metrics.commit_flush_errors.inc()
                log.exception(
                    "commit flush op (%s %s) raised unexpectedly",
                    op.verb, op.key,
                )
            finally:
                if op.trace_ctx is not None:
                    trace_context.restore(tok)
            if op.verb != "bind":
                # Bind outcomes land in the wire ring from the cache's
                # own finish_bind funnel (shared with the sync path);
                # recording them here too would double-count.
                trace.note_wire(
                    op.verb, op.key, flush_ok, cycle=op.trace_cycle,
                )
            done = time.monotonic()
            metrics.commit_flush_latency.observe(
                done - op.enqueued_at, op.verb
            )
            # SLO series feed (trace/slo.py): enqueue→ack latency per
            # op; the worker thread is scope-bound, so the observation
            # lands in the OWNING scheduler's engine.
            trace.slo_observe("commit_flush", done - op.enqueued_at)
            finalize = None
            with self._cv:
                self._running_keys[key] -= 1
                q = self._queues.get(key)
                if q:
                    self._ready.append(key)
                elif self._running_keys.get(key) == 0:
                    self._queues.pop(key, None)     # keys are pod uids:
                    self._running_keys.pop(key, None)  # don't leak them
                self._pending -= 1
                metrics.set_commit_queue_depth(self._pending)
                dur = done - started
                self._flush_busy_s += dur
                if overlapped or self._solving:
                    self._overlap_busy_s += dur
                if self._flush_busy_s > 0.0:
                    metrics.cycle_overlap_ratio.set(
                        self._overlap_busy_s / self._flush_busy_s
                    )
                b = self._batches.get(op.batch)
                if b is not None:
                    b["pending"] -= 1
                    b["last"] = done
                    if b["sealed"] and b["pending"] == 0:
                        first = b["first"] if b["first"] is not None else done
                        finalize = done - first
                        del self._batches[op.batch]
                        self.batches_completed += 1
                self._cv.notify_all()
            if finalize is not None:
                self._fire_on_flush(finalize)

    def _fire_on_flush(self, latency: float) -> None:
        if self._on_flush is None:
            return
        try:
            self._on_flush(latency)
        except Exception:  # noqa: BLE001 — observability must not kill flush
            log.exception("commit on_flush callback failed")

    # -- cycle hooks (scheduler loop) -----------------------------------
    def begin_cycle(self) -> None:
        """Seal the previous cycle's ops into a batch (its flush
        latency reports through ``on_flush`` when the last op lands)
        and open a fresh one for this cycle's enqueues."""
        finalize = None
        with self._cv:
            b = self._batches.get(self._batch_seq)
            if b is not None:
                b["sealed"] = True
                if b["pending"] == 0:
                    if b["first"] is not None:
                        finalize = (b["last"] or b["first"]) - b["first"]
                        self.batches_completed += 1
                    del self._batches[self._batch_seq]
            self._batch_seq += 1
            self._batches[self._batch_seq] = {
                "pending": 0, "first": None, "last": None, "sealed": False,
            }
        if finalize is not None:
            self._fire_on_flush(finalize)

    def note_solve(self, active: bool) -> None:
        """Scheduler hook bracketing in-cycle compute: flush busy-time
        spent while set is OVERLAPPED (hidden) work — the numerator of
        `cycle_overlap_ratio`."""
        with self._cv:
            self._solving = bool(active)

    # -- drain / shutdown ------------------------------------------------
    @property
    def depth(self) -> int:
        with self._cv:
            return self._pending

    def idle(self) -> bool:
        return self.depth == 0

    def drain(self, timeout: float | None = None) -> bool:
        """Block until every submitted op completed (True), or the
        timeout expires with work still in flight (False).  Never call
        from a flush worker."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._pending > 0:
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return False
                    self._cv.wait(remaining)
                else:
                    self._cv.wait(1.0)
            return True

    def close(self, timeout: float = 10.0) -> bool:
        """Drain (bounded), then stop the workers.  Later submits run
        inline.  Returns whether the drain completed."""
        ok = self.drain(timeout)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(1.0)
        # A closed pipeline no longer needs the teardown hook — and the
        # hook's strong reference would otherwise pin this pipeline
        # (and the whole cache world its closures capture) for process
        # lifetime across repeated chaos/bench/test constructions.
        atexit.unregister(self._atexit_close)
        return ok

    def _atexit_close(self) -> None:
        try:
            self.close(timeout=5.0)
        except Exception:  # noqa: BLE001 — best effort on the way down
            pass

    def stats(self) -> dict:
        with self._cv:
            return {
                "max_depth_seen": self.max_depth_seen,
                "depth": self._pending,
                "order_violations": self.order_violations,
                "flush_errors": self.flush_errors,
                "backpressure_waits": self.backpressure_waits,
                "batches_completed": self.batches_completed,
                "overlap_ratio": (
                    self._overlap_busy_s / self._flush_busy_s
                    if self._flush_busy_s > 0.0 else 0.0
                ),
            }
