"""TensorPolicy: the compile-time half of the session framework.

Reference counterpart: framework/session_plugins.go — the ~14 extension
point registries (AddJobOrderFn/AddPredicateFn/AddNodeOrderFn/
AddPreemptableFn/...) and their tiered evaluators.

Every registered fn is a pure jit-safe transform over
`(SnapshotTensors, AllocState)`.  Tier semantics are preserved exactly:
order fns stack into lexicographic keys (first decisive tier wins —
rank_from_keys), veto fns intersect within the first tier that has an
opinion.  Because fns are registered once per configuration and the
evaluators are plain compositions, the jitted cycle closures keep stable
identity and XLA compiles once per shape bucket.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import (
    SnapshotTensors,
    job_ready_counts,
    job_valid_counts,
)
from kube_batch_tpu.ops.assignment import (
    AllocState,
    _segment_prefix,
    rank_from_keys,
)

BIG_VTIME = 1e30


def virtual_start_times(
    seg: jax.Array,        # i32[T] segment id per task (queue or job)
    base_rank: jax.Array,  # i32[T] within-segment service order
    req: jax.Array,        # f32[T, R]
    valid: jax.Array,      # bool[T] tasks contending for placement now
    alloc_seg: jax.Array,  # f32[S, R] resources the segment already holds
    denom_seg: jax.Array,  # f32[S, R] fair-share denominator (deserved/total)
    num_segs: int,
) -> jax.Array:
    """f32[T]: weighted-fair-queueing virtual start times.

    The reference reaches fairness serially: after every placement the
    hungriest queue/job (lowest allocated/denominator share) is served
    next.  That trajectory is exactly service in order of *virtual start
    time* — the share the segment will have reached when this task's
    turn comes: max over resource dims of
        (alloc_seg + within-segment prefix of earlier tasks) / denom.
    Ranking tasks by this key reproduces the serial interleaving inside
    a single auction round (classic WFQ start-time scheduling), which is
    how DRF/proportion EventHandler feedback
    (plugins/drf/drf.go · OnSessionOpen handlers) survives batching.
    """
    r = jnp.where(valid[:, None], req, 0.0)
    segk = jnp.where(valid, jnp.clip(seg, 0, num_segs - 1), num_segs)
    perm, before, _ = _segment_prefix(segk, base_rank, r)
    s = jnp.clip(segk[perm], 0, num_segs - 1)
    start = alloc_seg[s] + before                       # f32[T, R]
    denom = denom_seg[s]
    ratio = jnp.where(
        denom > 0.0, start / jnp.maximum(denom, 1e-9),
        jnp.where(start > 0.0, BIG_VTIME, 0.0),
    )
    svt_sorted = jnp.max(ratio, axis=-1)
    return jnp.zeros(seg.shape[0], jnp.float32).at[perm].set(svt_sorted)

# fn signatures (all pure, jit-safe)
QueueKeyFn = Callable[[SnapshotTensors, AllocState], jax.Array]   # f32[Q]
JobKeyFn = Callable[[SnapshotTensors, AllocState], jax.Array]     # f32[J]
TaskKeyFn = Callable[[SnapshotTensors, AllocState], jax.Array]    # f32[T]
PredicateFn = Callable[[SnapshotTensors], jax.Array]              # bool[T, N]
NodeScoreFn = Callable[[SnapshotTensors, AllocState], jax.Array]  # f32[T, N]
JobBoolFn = Callable[[SnapshotTensors, AllocState], jax.Array]    # bool[J]
QueueBoolFn = Callable[[SnapshotTensors, AllocState], jax.Array]  # bool[Q]
# Veto fns see (snap, state, preemptor task index) → bool[T] over victims.
VetoFn = Callable[[SnapshotTensors, AllocState, jax.Array], jax.Array]
# Vtime fns see (snap, state, base_rank, valid) → f32[T] virtual start
# times; they carry share-feedback ordering at per-task granularity.
VtimeFn = Callable[
    [SnapshotTensors, AllocState, jax.Array, jax.Array], jax.Array
]


def task_queue_of(snap: SnapshotTensors) -> jax.Array:
    """i32[T]: each task's queue index via its job (padding → 0, masked)."""
    job = jnp.clip(snap.task_job, 0, snap.num_jobs - 1)
    return jnp.clip(snap.job_queue[job], 0, snap.num_queues - 1)


class TensorPolicy:
    """Aggregated plugin policy for one SchedulerConf."""

    def __init__(self, num_tiers: int) -> None:
        self.num_tiers = num_tiers
        self.queue_order: list[list[QueueKeyFn]] = [[] for _ in range(num_tiers)]
        # Namespace-level keys (f32[S]) sit between queue and job in the
        # rank hierarchy (≙ session_plugins.go · AddNamespaceOrderFn).
        self.namespace_order: list[list[JobKeyFn]] = [[] for _ in range(num_tiers)]
        self.job_order: list[list[JobKeyFn]] = [[] for _ in range(num_tiers)]
        self.task_order: list[list[TaskKeyFn]] = [[] for _ in range(num_tiers)]
        self.predicates: list[PredicateFn] = []
        # State-dependent predicates ((snap, state) -> bool[T, N]),
        # re-evaluated inside every auction round / preemption step —
        # inter-pod affinity lives here, because feasibility depends on
        # placements made earlier in the same cycle (the reference gets
        # this for free from its serial per-task PredicateNodes calls).
        # Each entry is (full_fn, row_fn|None, subset_fn|None):
        # row_fn(snap, state, p) -> bool[N] lets the preemption kernel
        # evaluate one task without materializing [T, N] every step;
        # subset_fn(snap, state, sub_snap, sub_state, immediate) ->
        # bool[P, N] evaluates a gathered task subset against
        # full-cluster residents (see add_dynamic_predicate_fn).
        self.dynamic_predicates: list[tuple[NodeScoreFn, object, object]] = []
        # bool[T] masks of tasks that must be accepted at most one per
        # auction round globally (affinity bootstrap claimants).
        self.global_serialize: list = []
        # bool[T] masks of tasks limited to one acceptance per topology
        # DOMAIN per round (domain-scoped anti-affinity participants).
        self.domain_serialize: list = []
        self.node_scores: list[tuple[float, NodeScoreFn]] = []
        self.job_valid: list[JobBoolFn] = []
        self.job_ready: list[JobBoolFn] = []
        self.job_pipelined: list[JobBoolFn] = []
        self.overused: list[QueueBoolFn] = []
        self.queue_vtime: list[list[VtimeFn]] = [[] for _ in range(num_tiers)]
        self.ns_vtime: list[list[VtimeFn]] = [[] for _ in range(num_tiers)]
        self.job_vtime: list[list[VtimeFn]] = [[] for _ in range(num_tiers)]
        self.cycle_setup: list[tuple[str, Callable]] = []
        self.preemptable: list[list[VetoFn]] = [[] for _ in range(num_tiers)]
        self.reclaimable: list[list[VetoFn]] = [[] for _ in range(num_tiers)]
        self._dynamic_scores = False
        # Score grid for the allocate auction (see ops/assignment.py ·
        # allocate_rounds score_quantum).  Set when state-dependent
        # scores register; plugins may override via their Arguments.
        self.score_quantum = 0.0
        # Auction round cap (operator latency valve; scheduler.conf
        # top-level `arguments: {allocate.max_rounds: N}`).  None =
        # exact: run to the fixed point.  Under oversubscription the
        # serial-fidelity watermark places the scarce tail one rank
        # burst per round (BASELINE.md round-5 attribution: config 4
        # converges in ~128 rounds, ~4 ms each on TPU); capping bounds
        # cycle latency and leaves the remainder Pending for the next
        # cycle — the same degradation the reference exhibits when its
        # serial cycle overruns the 1 s period.
        self.max_rounds: int | None = None

    # -- registration (≙ session_plugins.go Add*Fn) ---------------------
    def add_queue_order_fn(self, tier: int, fn: QueueKeyFn) -> None:
        self.queue_order[tier].append(fn)

    def add_namespace_order_fn(self, tier: int, fn) -> None:
        self.namespace_order[tier].append(fn)

    def add_namespace_vtime_fn(self, tier: int, fn: VtimeFn) -> None:
        self.ns_vtime[tier].append(fn)

    def add_job_order_fn(self, tier: int, fn: JobKeyFn) -> None:
        self.job_order[tier].append(fn)

    def add_task_order_fn(self, tier: int, fn: TaskKeyFn) -> None:
        self.task_order[tier].append(fn)

    def add_predicate_fn(self, fn: PredicateFn) -> None:
        self.predicates.append(fn)

    def add_dynamic_predicate_fn(
        self, fn: NodeScoreFn, row_fn=None, subset_fn=None
    ) -> None:
        """`subset_fn(snap, state, sub_snap, sub_state, immediate) ->
        bool[P, N]`, when provided, evaluates the predicate for a
        GATHERED task subset (packer.gather_tasks) while reading
        residents/aggregates from the FULL snapshot+state — the
        active-set seam that lets [T, N] passes shrink to [P, N]
        without losing sight of placed tasks."""
        self.dynamic_predicates.append((fn, row_fn, subset_fn))

    def add_global_serialize_fn(self, fn) -> None:
        self.global_serialize.append(fn)

    def add_domain_serialize_fn(self, fn) -> None:
        self.domain_serialize.append(fn)

    def add_node_order_fn(
        self, weight: float, fn: NodeScoreFn, state_dependent: bool = True
    ) -> None:
        """`state_dependent` marks scores that read the live AllocState
        (least-requested etc.).  Their presence turns on score
        quantization in allocate: the serial reference re-scores after
        every placement; the auction approximates that by flooring
        scores to a grid so near-equal nodes tie and spread, with
        divergence bounded by the quantum (see allocate_rounds)."""
        self.node_scores.append((weight, fn))
        if state_dependent:
            self._dynamic_scores = True
            if self.score_quantum == 0.0:
                self.score_quantum = 0.5

    @property
    def has_dynamic_scores(self) -> bool:
        return self._dynamic_scores

    def add_job_valid_fn(self, fn: JobBoolFn) -> None:
        self.job_valid.append(fn)

    def add_job_ready_fn(self, fn: JobBoolFn) -> None:
        self.job_ready.append(fn)

    def add_job_pipelined_fn(self, fn: JobBoolFn) -> None:
        self.job_pipelined.append(fn)

    def add_overused_fn(self, fn: QueueBoolFn) -> None:
        self.overused.append(fn)

    def add_queue_vtime_fn(self, tier: int, fn: VtimeFn) -> None:
        self.queue_vtime[tier].append(fn)

    def add_job_vtime_fn(self, tier: int, fn: VtimeFn) -> None:
        self.job_vtime[tier].append(fn)

    def add_cycle_setup_fn(self, name: str, fn) -> None:
        """Register a snapshot-only tensor computed once per cycle and
        carried in AllocState.aux[name] (hoists loop-invariant plugin
        work out of the auction rounds)."""
        self.cycle_setup.append((name, fn))

    def setup_state(self, snap: SnapshotTensors, state: AllocState) -> AllocState:
        """Populate AllocState.aux with the registered per-cycle tensors
        (call at the top of every jitted solve)."""
        if not self.cycle_setup:
            return state
        aux = dict(state.aux)
        for name, fn in self.cycle_setup:
            aux[name] = fn(snap)
        return state.replace(aux=aux)

    def add_preemptable_fn(self, tier: int, fn: VetoFn) -> None:
        self.preemptable[tier].append(fn)

    def add_reclaimable_fn(self, tier: int, fn: VetoFn) -> None:
        self.reclaimable[tier].append(fn)

    # -- evaluators -----------------------------------------------------
    def predicate_mask(self, snap: SnapshotTensors) -> jax.Array:
        """bool[T, N]: AND of all plugin predicates (chained like the
        reference's predicate list — any veto excludes the node)."""
        m = jnp.ones((snap.num_tasks, snap.num_nodes), bool)
        for fn in self.predicates:
            m = m & fn(snap)
        return m

    def dynamic_predicate_fn(
        self,
        snap: SnapshotTensors,
        state: AllocState,
        immediate: bool = False,
    ):
        """bool[T, N] AND of the registered state-dependent predicates,
        or None when none are registered (kernels skip the per-round
        evaluation entirely).  `immediate` is True for the Idle pass
        (placements binding this cycle) — predicates may check against
        still-terminating residents there (see
        plugins/predicates.py · pod_affinity_predicate)."""
        if not self.dynamic_predicates:
            return None
        m = jnp.ones((snap.num_tasks, snap.num_nodes), bool)
        for fn, _row, _sub in self.dynamic_predicates:
            m = m & fn(snap, state, immediate)
        return m

    def dynamic_predicate_subset_fn(
        self, snap, state, sub_snap, sub_state, immediate: bool = False
    ):
        """bool[P, N] AND of the dynamic predicates evaluated for a
        gathered task subset against FULL-cluster residents, or None
        when no dynamic predicates are registered OR any registered one
        lacks a subset variant (the caller must then fall back to the
        full [T, N] evaluation)."""
        if not self.dynamic_predicates:
            return None
        if not self.has_subset_dynamic_predicates:
            return None
        m = jnp.ones((sub_snap.num_tasks, snap.num_nodes), bool)
        for _fn, _row, sub in self.dynamic_predicates:
            m = m & sub(snap, state, sub_snap, sub_state, immediate)
        return m

    @property
    def has_subset_dynamic_predicates(self) -> bool:
        """True when the subset path is available: either no dynamic
        predicates at all, or every one carries a subset variant."""
        return all(sub is not None for _f, _r, sub in self.dynamic_predicates)

    @property
    def dyn_predicate(self):
        """The callable to hand kernels (None when unused)."""
        if not self.dynamic_predicates:
            return None
        return self.dynamic_predicate_fn

    @property
    def dyn_predicate_row(self):
        """(snap, state, p) -> bool[N] single-task variant (None when no
        dynamic predicates are registered)."""
        if not self.dynamic_predicates:
            return None
        entries = list(self.dynamic_predicates)

        def row(snap, state, p):
            m = jnp.ones(snap.num_nodes, bool)
            for fn, row_fn, _sub in entries:
                m = m & (
                    row_fn(snap, state, p)
                    if row_fn is not None
                    else fn(snap, state)[p]
                )
            return m

        return row

    @property
    def global_serialize_fn(self):
        """(snap, state) -> bool[T] of tasks limited to one acceptance
        per auction round across the whole cluster (None when unused)."""
        return self._or_of(self.global_serialize)

    @property
    def domain_serialize_fn(self):
        """(snap, state) -> bool[T] of tasks limited to one acceptance
        per topology domain per round (None when unused)."""
        return self._or_of(self.domain_serialize)

    @staticmethod
    def _or_of(fns_list):
        if not fns_list:
            return None
        fns = list(fns_list)

        def mask(snap, state):
            m = jnp.zeros(snap.num_tasks, bool)
            for fn in fns:
                m = m | fn(snap, state)
            return m

        return mask

    def score_fn(self, snap: SnapshotTensors, state: AllocState) -> jax.Array:
        """f32[T, N]: weighted sum of node-order scores
        (≙ util.PrioritizeNodes summing weighted priority fns)."""
        s = jnp.zeros((snap.num_tasks, snap.num_nodes), jnp.float32)
        for w, fn in self.node_scores:
            s = s + w * fn(snap, state)
        return s

    def rank_fn(self, snap: SnapshotTensors, state: AllocState) -> jax.Array:
        """i32[T]: global scheduling-order ranks from the tiered
        queue > job > task lexicographic ordering.

        When vtime fns are registered (drf/proportion), their
        virtual-start-time keys slot in AT THEIR OWN TIER of their own
        level: a vtime dominates its tier's static keys and everything
        less significant, but stays subordinate to HIGHER tiers of the
        same level and to higher levels — drf's tier-2 share WFQ must
        never reorder across tier-1 priority (the reference's tiered
        JobOrderFn decides priority first; share feedback only
        interleaves jobs the decisive tiers left tied).  Each vtime is
        computed with the so-far-accumulated rank as its within-segment
        service order, so the per-task interleaving inside a segment
        reproduces the reference's one-pod-at-a-time share feedback."""
        from kube_batch_tpu.api.types import TaskStatus

        tq = task_queue_of(snap)
        tj = jnp.clip(snap.task_job, 0, snap.num_jobs - 1)
        tns = jnp.clip(snap.task_ns, 0, snap.ns_weight.shape[0] - 1)
        vtime_levels = [self.job_vtime, self.ns_vtime, self.queue_vtime]
        have_vtime = any(any(map(len, level)) for level in vtime_levels)
        if have_vtime:
            pending = (
                state.task_state == int(TaskStatus.PENDING)
            ) & snap.task_mask
            valid = pending & self.eligible_fn(snap, state)

        # least-significant-first; within each level, later tiers are
        # less significant than earlier ones.
        keys: list[jax.Array] = [snap.task_order.astype(jnp.float32)]
        for tier_fns in reversed(self.task_order):
            for fn in reversed(tier_fns):
                keys.append(fn(snap, state))

        def level(static_fns, vtime_fns, gather):
            for t in range(len(static_fns) - 1, -1, -1):
                for fn in reversed(static_fns[t]):
                    keys.append(gather(fn(snap, state)))
                # reversed like the static keys: later-registered =
                # less significant, so it must be appended FIRST.
                for fn in reversed(vtime_fns[t]):
                    base = rank_from_keys(keys, snap.num_tasks)
                    keys.append(fn(snap, state, base, valid))

        level(self.job_order, self.job_vtime, lambda k: k[tj])
        level(self.namespace_order, self.ns_vtime, lambda k: k[tns])
        level(self.queue_order, self.queue_vtime, lambda k: k[tq])
        return rank_from_keys(keys, snap.num_tasks)

    def job_rank(self, snap: SnapshotTensors, state: AllocState) -> jax.Array:
        """i32[J]: job-level ranks (used by preempt's starving-job order)."""
        keys: list[jax.Array] = [snap.job_order.astype(jnp.float32)]
        for tier_fns in reversed(self.job_order):
            for fn in reversed(tier_fns):
                keys.append(fn(snap, state))
        return rank_from_keys(keys, snap.num_jobs)

    def job_valid_mask(self, snap: SnapshotTensors, state: AllocState) -> jax.Array:
        """bool[J] (≙ ssn.JobValid; no fns → all valid)."""
        m = snap.job_mask
        for fn in self.job_valid:
            m = m & fn(snap, state)
        return m

    def job_ready_mask(self, snap: SnapshotTensors, state: AllocState) -> jax.Array:
        """bool[J] (≙ ssn.JobReady; no fns → all ready)."""
        m = snap.job_mask
        for fn in self.job_ready:
            m = m & fn(snap, state)
        return m

    def job_pipelined_mask(self, snap: SnapshotTensors, state: AllocState) -> jax.Array:
        """bool[J] (≙ ssn.JobPipelined): would the gang gate be met once
        pipelined placements land?  Consulted by preempt — a job whose
        minMember is satisfiable by releasing resources shouldn't evict
        victims for it."""
        m = snap.job_mask
        for fn in self.job_pipelined:
            m = m & fn(snap, state)
        return m

    def overused_mask(self, snap: SnapshotTensors, state: AllocState) -> jax.Array:
        """bool[Q] (≙ ssn.Overused; OR — any plugin can declare overuse)."""
        m = jnp.zeros(snap.num_queues, bool)
        for fn in self.overused:
            m = m | fn(snap, state)
        return m

    def eligible_fn(self, snap: SnapshotTensors, state: AllocState) -> jax.Array:
        """bool[T]: may this pending task be placed right now — its job
        valid (gang), its queue not overused (proportion)."""
        jv = self.job_valid_mask(snap, state)
        over = self.overused_mask(snap, state)
        tj = jnp.clip(snap.task_job, 0, snap.num_jobs - 1)
        tq = task_queue_of(snap)
        return jv[tj] & ~over[tq] & (snap.task_job >= 0)

    def _veto_intersection(
        self,
        tiers: list[list[VetoFn]],
        snap: SnapshotTensors,
        state: AllocState,
        preemptor: jax.Array,
    ) -> jax.Array:
        """bool[T] victim permission: within the FIRST tier that has any
        registered fn, intersect plugin answers; later tiers are ignored
        (≙ session_plugins.go · Preemptable/Reclaimable tier walk, which
        returns at the first tier whose plugins produced a decision).
        Under the default config tier 1 (gang/conformance) is decisive —
        tier-2 vetoes like proportion's deserved floor never bind here,
        exactly as upstream; reclaim's stop-at-deserved lives as an
        inline check in the reclaim action instead (≙ reclaim.go)."""
        for tier_fns in tiers:
            if tier_fns:
                m = jnp.ones(snap.num_tasks, bool)
                for fn in tier_fns:
                    m = m & fn(snap, state, preemptor)
                return m
        return jnp.ones(snap.num_tasks, bool)

    def preemptable_mask(
        self, snap: SnapshotTensors, state: AllocState, preemptor: jax.Array
    ) -> jax.Array:
        return self._veto_intersection(self.preemptable, snap, state, preemptor)

    def reclaimable_mask(
        self, snap: SnapshotTensors, state: AllocState, preemptor: jax.Array
    ) -> jax.Array:
        return self._veto_intersection(self.reclaimable, snap, state, preemptor)

    # -- convenience reductions ----------------------------------------
    @staticmethod
    def ready_counts(snap: SnapshotTensors, state: AllocState) -> jax.Array:
        return job_ready_counts(snap, state.task_state)

    @staticmethod
    def valid_counts(snap: SnapshotTensors, state: AllocState) -> jax.Array:
        return job_valid_counts(snap, state.task_state)
