"""Session framework: the plugin/action runtime.

Reference counterpart: pkg/scheduler/framework (OpenSession/CloseSession,
Session with its ~14 extension-point registries, Statement transactions,
plugin/action registries).

TPU-native split: registration is divided into a **compile-time half**
(`TensorPolicy` — pure jit-safe tensor transforms, registered once per
config so jitted cycle functions keep stable identity and XLA's compile
cache works across cycles) and a **runtime half** (`Session` — one
snapshot's host state, per-cycle open/close hooks, and the commit funnel
back to the cache).  The reference re-registers everything every cycle
because closures are free in Go; under XLA, stable function identity IS
the compile cache key, so the split is load-bearing.
"""

from kube_batch_tpu.framework.plugin import (
    Plugin,
    Action,
    register_plugin,
    register_action,
    get_plugin_builder,
    get_action,
    PLUGIN_REGISTRY,
    ACTION_REGISTRY,
)
from kube_batch_tpu.framework.conf import (
    PluginConf,
    TierConf,
    SchedulerConf,
    default_conf,
)
from kube_batch_tpu.framework.policy import TensorPolicy
from kube_batch_tpu.framework.session import Session, open_session, close_session

__all__ = [
    "Plugin",
    "Action",
    "register_plugin",
    "register_action",
    "get_plugin_builder",
    "get_action",
    "PLUGIN_REGISTRY",
    "ACTION_REGISTRY",
    "PluginConf",
    "TierConf",
    "SchedulerConf",
    "default_conf",
    "TensorPolicy",
    "Session",
    "open_session",
    "close_session",
]
