"""Session: one scheduling cycle's runtime state and commit funnel.

Reference counterpart: framework/framework.go (OpenSession/CloseSession)
and framework/session.go (Session.Allocate/Pipeline/Evict/dispatch).

A Session owns one packed snapshot and threads an `AllocState` through
the configured actions.  Cluster effects happen only at two funnels:

* `commit_evictions` — preempt/reclaim land their victim evictions
  (their transactional what-if is pure tensor math; commit-or-drop is
  simply whether the delta is applied, ≙ Statement.Commit/Discard);
* `close_session` — binds dispatch for every job passing the JobReady
  gate (gang all-or-nothing: an unready job's tentative placements are
  dropped with zero cluster effect, ≙ session.go deferring dispatch
  until JobReady).
"""

from __future__ import annotations

import atexit
import itertools
from typing import Sequence

import numpy as np

from kube_batch_tpu import metrics, trace
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.cache.packer import pack_snapshot
from kube_batch_tpu.framework.conf import SchedulerConf
from kube_batch_tpu.framework.plugin import Plugin, get_plugin_builder
from kube_batch_tpu.framework.policy import TensorPolicy
from kube_batch_tpu.ops.assignment import AllocState, init_state

_BIND_POOL = None


def _bind_pool():
    """Process-shared bind fan-out pool, created on first large gang
    commit and reused across cycles — worker threads must SURVIVE
    between cycles so backend keep-alive state tied to them (e.g.
    K8sHttpBackend's thread-local connections) keeps amortizing its
    TCP+TLS setup instead of reconnecting every commit."""
    global _BIND_POOL
    if _BIND_POOL is None:
        from concurrent.futures import ThreadPoolExecutor

        _BIND_POOL = ThreadPoolExecutor(
            max_workers=Session.BIND_WORKERS,
            thread_name_prefix="bind-dispatch",
        )
    return _BIND_POOL


def shutdown_bind_pool(wait: bool = False) -> None:
    """Tear down the process-global bind fan-out pool.  Registered
    atexit (and callable explicitly by daemon shutdown paths) so a
    worker mid-wire-call cannot race interpreter teardown the way the
    growth-compile threads once did — queued-but-unstarted binds are
    cancelled, and a later `_bind_pool()` call simply builds a fresh
    pool.  The commit pipeline's flush executor applies the same
    discipline (framework/commit.py · CommitPipeline.close, also
    atexit-registered)."""
    global _BIND_POOL
    pool, _BIND_POOL = _BIND_POOL, None
    if pool is not None:
        pool.shutdown(wait=wait, cancel_futures=True)


atexit.register(shutdown_bind_pool)

_session_counter = itertools.count()


def build_policy(conf: SchedulerConf) -> tuple[TensorPolicy, list[Plugin]]:
    """Instantiate plugins from conf and let them register their tensor
    fns — once per configuration (≙ every-cycle OnSessionOpen in the
    reference, hoisted to config time because fn identity is the XLA
    compile-cache key here)."""
    # Hand-built SchedulerConfs can reach here without ever touching
    # default_conf(); plugin lookups below must not depend on the
    # caller's import graph (framework/plugin.py · ensure_registered).
    from kube_batch_tpu.framework.plugin import ensure_registered

    ensure_registered()

    policy = TensorPolicy(num_tiers=len(conf.tiers))
    # Loud validation (same posture as world-file section checks): a
    # typo'd knob or a nonsense value must fail the conf build — the
    # hot-reload path then keeps serving the previous policy and logs
    # the error, instead of silently no-opping the operator's intent.
    args = conf.args_dict
    unknown = set(args) - {"allocate.max_rounds"}
    if unknown:
        raise ValueError(
            f"unknown scheduler.conf arguments: {sorted(unknown)} "
            "(supported: allocate.max_rounds)"
        )
    if "allocate.max_rounds" in args:
        mr = args["allocate.max_rounds"]
        if isinstance(mr, bool) or not isinstance(mr, int) or mr < 1:
            # No silent coercion: 2.5, "4", or true must fail the
            # build, not be quietly reinterpreted.
            raise ValueError(
                f"allocate.max_rounds must be an integer >= 1, got "
                f"{mr!r} (omit the key for the exact fixed-point solve)"
            )
        policy.max_rounds = mr
    plugins: list[Plugin] = []
    for tier_idx, tier in enumerate(conf.tiers):
        for pconf in tier.plugins:
            plugin = get_plugin_builder(pconf.name)(pconf.args_dict)
            plugin.set_enabled(dict(pconf.enabled))
            plugin.register(policy, tier_idx)
            plugins.append(plugin)
    return policy, plugins


class Session:
    """One cycle: snapshot in, bind/evict decisions out."""

    def __init__(
        self,
        cache: SchedulerCache,
        policy: TensorPolicy,
        plugins: Sequence[Plugin],
        packer=None,
    ) -> None:
        self.uid = next(_session_counter)
        self.cache = cache
        self.policy = policy
        self.plugins = list(plugins)

        # Shared snapshot + pack as ONE critical section: the packer
        # reads live Pod fields, so it must finish under the cache lock
        # (≙ the reference holding its mutex for the whole Snapshot deep
        # copy).  This removes the per-pod copy loop — the single
        # largest host cost of a cycle at 50k pods — while keeping the
        # adapter thread's mutations strictly before-or-after the view.
        #
        # With an IncrementalPacker (the daemon path), the pack itself
        # is event-driven: only rows whose pods/nodes changed since the
        # previous cycle are touched (see cache/incremental.py).
        with metrics.snapshot_pack_latency.time():
            if packer is not None:
                self.host = None
                self.snap, self.meta = packer.pack()
                # The packer already holds the padded host task_state —
                # reading it back from the device would cost a tunnel
                # round trip per cycle for bytes the host still has.
                self.initial_task_state = packer.host_task_state()
            else:
                with cache.lock():
                    self.host = cache.snapshot(shared=True)
                    self.snap, self.meta = pack_snapshot(self.host)
                self.initial_task_state = np.asarray(self.snap.task_state)
        # Lazily materialized (see the `state` property).
        self._packer = packer
        self._state: AllocState | None = None
        self._host_fields: dict[str, np.ndarray] = {}
        # PodGroups whose statuses need recomputing at close: the
        # groups this pack's mutations touched (None = all — full
        # rebuilds and the packer-less path).  This cycle's binds and
        # evictions add their groups as they land.
        self._refresh_groups: set[str] | None = (
            set(packer.last_groups)
            if packer is not None and packer.last_groups is not None
            else None
        )

        self.bound: list[tuple[str, str]] = []     # (pod name, node) this cycle
        self.evicted: list[tuple[str, str]] = []   # (pod name, reason)
        # JobReady cache: the fused cycle computes the mask on-device as
        # part of its single dispatch and stores it here, so
        # dispatch_binds/unready_jobs need no extra device round trip.
        self._job_ready: np.ndarray | None = None
        # Host copies of the FINAL task_state/task_node, filled at first
        # post-action read (or in one batched transfer by the fused
        # path via set_host_final) — every later consumer (bind
        # dispatch, pending gauge, diagnosis, the loop's result label)
        # reuses them instead of paying another D2H round trip on the
        # tunneled backend.
        self._host_task_state: np.ndarray | None = None
        self._host_task_node: np.ndarray | None = None
        self._diag = None  # precomputed diagnosis (fused cycle only)

    @property
    def state(self) -> AllocState:
        """Initial AllocState, materialized on first use.  With a
        packer, it is built from the packer's HOST arrays: numpy leaves
        ride the jitted cycle's own argument transfer, so the daemon
        pays no separate device dispatch for state init (the eager
        `node_idle + node_releasing` add costs a full tunnel round
        trip per cycle otherwise).  Folding init_state INTO the jitted
        cycle is not an option: it flips XLA:TPU into a pathological
        compile at flagship shapes (see Scheduler._ensure_compiled)."""
        if self._state is None:
            if self._packer is not None:
                self._state = self._packer.host_alloc_state()
            else:
                self._state = init_state(self.snap)
        return self._state

    @state.setter
    def state(self, value: AllocState) -> None:
        self._state = value

    def host_snap_field(self, name: str) -> np.ndarray:
        """Read-only host view of a STATIC snapshot field, cached per
        session — served from the packer's host arrays when available,
        because a per-cycle device read of bytes the host already holds
        costs a full tunnel round trip (~45-70 ms each; three such
        reads were most of close_session's cost at flagship scale).
        The packer hands out non-writeable views, so accidental
        mutation of its live patch state raises instead of corrupting
        later packs."""
        arr = self._host_fields.get(name)
        if arr is None:
            if self._packer is not None:
                arr = self._packer.host_field(name)
            if arr is None:
                arr = np.asarray(getattr(self.snap, name))
            self._host_fields[name] = arr
        return arr

    def host_task_state(self) -> np.ndarray:
        """i32[T] host copy of the live task_state (cached; call only
        after the cycle's actions have finished mutating self.state)."""
        if self._host_task_state is None:
            self._host_task_state = np.asarray(self.state.task_state)
        return self._host_task_state

    def host_task_node(self) -> np.ndarray:
        """i32[T] host copy of the live task_node (cached like
        host_task_state)."""
        if self._host_task_node is None:
            self._host_task_node = np.asarray(self.state.task_node)
        return self._host_task_node

    def set_host_final(
        self, task_state: np.ndarray, task_node: np.ndarray
    ) -> None:
        """Install host copies fetched in the fused cycle's one batched
        device_get."""
        self._host_task_state = np.asarray(task_state)
        self._host_task_node = np.asarray(task_node)

    def job_ready(self) -> np.ndarray:
        """bool[J] host copy of the gang commit gate (cached)."""
        if self._job_ready is None:
            self._job_ready = np.asarray(
                self.policy.job_ready_mask(self.snap, self.state)
            )
        return self._job_ready

    def set_job_ready(self, mask: np.ndarray) -> None:
        self._job_ready = np.asarray(mask)

    def set_diagnosis(self, diag) -> None:
        """Why-unschedulable failure tallies computed inside the fused
        cycle's dispatch (see actions/fused.py) — diagnose_pending uses
        them instead of compiling a second device program."""
        self._diag = diag

    # -- commit funnels -------------------------------------------------
    def commit_evictions(self, victim_idx: Sequence[int], reason: str) -> None:
        """Land evictions decided by preempt/reclaim (≙ Statement.Commit
        replaying Evict through the cache)."""
        dlog = trace.decision_log()
        cyc = trace.current_cycle()
        for t in victim_idx:
            pod = self.meta.task_pods[int(t)]
            # The victim's node, read BEFORE the eviction mutates it:
            # the decision record's vacated-node entry is what
            # attributes the later beneficiary placement.
            node = pod.node
            if self.cache.evict(pod.uid, reason):
                self.evicted.append((pod.name, reason))
                if self._refresh_groups is not None and pod.group:
                    self._refresh_groups.add(pod.group)
                metrics.pods_evicted.inc(reason)
                if dlog is not None:
                    dlog.note_eviction(
                        pod.uid, pod.name, pod.group, node, reason, cyc,
                    )

    #: Bind fan-out width (≙ the reference's async bind goroutines /
    #: its 16-worker helper pools): each bind through a wire backend is
    #: a full round trip, and a 47.5k-pod gang commit at a ~68 ms RTT
    #: would take the better part of an hour dispatched serially.
    BIND_WORKERS = 16
    #: Below this many binds the pool costs more than it saves (the
    #: in-process simulator path binds in microseconds).
    _BIND_POOL_THRESHOLD = 64

    def dispatch_binds(self) -> list[tuple[str, str]]:
        """Bind every newly allocated task of every JobReady job
        (gang commit; ≙ session.go · Allocate's deferred dispatch).

        With an asynchronous commit pipeline attached to the cache
        (`cache.commit`, wire mode's default), each bind's CACHE half
        lands here synchronously (`begin_bind` marks BINDING, so the
        next cycle's pack can never re-place the pod) and the wire
        round trip flushes on the pipeline keyed by pod uid — the
        cycle's `bind_dispatch` phase is then ENQUEUE time, and cycle
        N's RTTs overlap cycle N+1's solve.

        Synchronous path (simulator, --wire-commit sync): large
        batches fan out over a thread pool; `cache.bind` is
        thread-safe (mutations under the cache lock, the backend call
        outside it) and result ORDER is preserved, so `self.bound` is
        deterministic either way.  Bookkeeping (bound list, refresh
        groups) stays on this thread."""
        task_state = self.host_task_state()
        task_node = self.host_task_node()
        task_job = self.host_snap_field("task_job")

        newly_allocated = (
            (task_state == int(TaskStatus.ALLOCATED))
            & (self.initial_task_state == int(TaskStatus.PENDING))
        )
        newly_idx = np.nonzero(newly_allocated)[0]
        # Nothing newly allocated (e.g. a ceiling-paused cycle that
        # never ran the solve): don't touch job_ready — its fallback
        # computes the gang mask on-device, a dispatch (and at a new
        # shape, a compile) this cycle deliberately avoided.
        ready = self.job_ready() if newly_idx.size else None
        to_bind: list[tuple[object, str]] = []
        # Decision records (kube_batch_tpu/trace/): gang-gated drops
        # per job and landed placements, recorded only while tracing is
        # enabled — `gated is None` keeps the disabled path free of
        # bookkeeping.
        gated: dict[int, int] | None = {} if trace.enabled() else None
        for t in newly_idx:
            if t >= self.meta.num_real_tasks:
                continue
            j = task_job[t]
            if j < 0 or not ready[j]:
                if gated is not None and j >= 0:
                    gated[int(j)] = gated.get(int(j), 0) + 1
                continue  # gang gate: unready job's placements are dropped
            to_bind.append((
                self.meta.task_pods[t],
                self.meta.node_names[task_node[t]],
            ))
        if gated:
            dlog = trace.decision_log()
            cyc = trace.current_cycle()
            for j, dropped in gated.items():
                dlog.note_group(
                    self.meta.job_names[j], "gang-gated", cyc,
                    placements_dropped=dropped,
                )

        placed: list = []
        commit = getattr(self.cache, "commit", None)
        if commit is not None:
            # Pipelined: the cache mutation is the cycle's commit; the
            # wire RTT flushes later.  A pod whose begin_bind refused
            # (deleted, or its node vanished) is already resynced by
            # the cache — same outcome as a failed sync bind.
            for pod, node_name in to_bind:
                if not self.cache.begin_bind(pod.uid, node_name):
                    continue
                commit.submit_bind(pod.uid, node_name)
                self.bound.append((pod.name, node_name))
                placed.append((pod, node_name))
                if self._refresh_groups is not None and pod.group:
                    self._refresh_groups.add(pod.group)
            self._note_placed(placed)
            return self.bound
        if len(to_bind) > self._BIND_POOL_THRESHOLD:
            results = list(_bind_pool().map(
                lambda a: self.cache.bind(a[0].uid, a[1]), to_bind
            ))
        else:
            results = [
                self.cache.bind(pod.uid, node) for pod, node in to_bind
            ]
        for (pod, node_name), ok in zip(to_bind, results):
            if ok:
                self.bound.append((pod.name, node_name))
                placed.append((pod, node_name))
                if self._refresh_groups is not None and pod.group:
                    self._refresh_groups.add(pod.group)
        self._note_placed(placed)
        return self.bound

    @staticmethod
    def _note_placed(placed: list) -> None:
        """Feed landed binds to the decision log (victim→beneficiary
        attribution happens inside note_placed when the node was
        recently vacated by an eviction)."""
        if not placed:
            return
        dlog = trace.decision_log()
        if dlog is None:
            return
        cyc = trace.current_cycle()
        for pod, node_name in placed:
            dlog.note_placed(pod.uid, pod.name, pod.group, node_name, cyc)

    # -- introspection for plugins' close hooks ------------------------
    def snapshot_ready_counts(self) -> np.ndarray:
        """i32[J]: ready members per job AS OF THE PACKED SNAPSHOT —
        computed from the frozen tensor copy, not live Pod statuses
        (the shared snapshot's pods keep mutating after the lock is
        released; see cache.snapshot(shared=True))."""
        from kube_batch_tpu.api.types import READY_STATUSES

        ready = np.isin(
            self.initial_task_state,
            [int(s) for s in READY_STATUSES],
        )
        task_job = self.host_snap_field("task_job")
        J = int(self.snap.num_jobs)
        valid = ready & (task_job >= 0)
        return np.bincount(
            task_job[valid], minlength=J
        ).astype(np.int64)[:J]

    def unready_jobs(self) -> list[str]:
        """Names of jobs that wanted resources but failed the gang gate."""
        ready = self.job_ready()
        out = []
        for j, name in enumerate(self.meta.job_names):
            if not ready[j]:
                out.append(name)
        return out


def open_session(
    cache: SchedulerCache,
    policy: TensorPolicy,
    plugins: Sequence[Plugin],
    packer=None,
) -> Session:
    """≙ framework.go · OpenSession: snapshot + plugin open hooks."""
    ssn = Session(cache, policy, plugins, packer=packer)
    for plugin in ssn.plugins:
        with metrics.plugin_latency.time(plugin.name, "open"):
            plugin.on_session_open(ssn)
    return ssn


def close_session(ssn: Session, diagnose: bool = True) -> None:
    """≙ framework.go · CloseSession: dispatch gang-gated binds, emit
    why-unschedulable events, run plugin close hooks (events/
    conditions), write back job status."""
    from kube_batch_tpu.framework.fit_errors import diagnose_pending

    with metrics.cycle_phase_latency.time("bind_dispatch"), \
            trace.span("dispatch"):
        ssn.dispatch_binds()
    if diagnose:
        with metrics.cycle_phase_latency.time("diagnosis"), \
                trace.span("diagnosis"):
            for pod_name, namespace, message in diagnose_pending(ssn):
                ssn.cache.record_event(
                    "Pod" if pod_name else "Scheduler",
                    pod_name, "FailedScheduling", message,
                    namespace=namespace,
                )
    for plugin in ssn.plugins:
        with metrics.plugin_latency.time(plugin.name, "close"):
            plugin.on_session_close(ssn)
    # Status writeback against the LIVE cache jobs, so phases reflect
    # this cycle's binds/evictions (≙ job_updater.go batching PodGroup
    # status updates at CloseSession).  With an incremental packer the
    # recompute is targeted: only groups this pack's mutations touched
    # plus this cycle's bind/evict groups can have changed status —
    # recomputing all ~thousands of jobs is O(total tasks) of host
    # Python per cycle for identical results.
    # None = refresh ALL live cache jobs, not the snapshot's job list:
    # a job orphaned by queue deletion leaves the snapshot but still
    # needs its phase corrected (Inqueue → Pending) on the full-rebuild
    # cycle the deletion forces.
    with metrics.cycle_phase_latency.time("status_writeback"), \
            trace.span("status_writeback"):
        ssn.cache.refresh_job_statuses(ssn._refresh_groups)
    metrics.pending_tasks.set(
        float(
            np.sum(
                ssn.host_task_state()[: ssn.meta.num_real_tasks]
                == int(TaskStatus.PENDING)
            )
        )
    )
