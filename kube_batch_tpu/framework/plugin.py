"""Plugin/Action interfaces and registries.

Reference counterpart: pkg/scheduler/framework/interface.go (Plugin,
Action), plugins.go (RegisterPluginBuilder/GetPluginBuilder) and
actions/factory.go (action registration — BASELINE.json names it
framework.RegisterAction, which is where it lives here).
"""

from __future__ import annotations

from typing import TYPE_CHECKING, Any, Callable, Mapping

if TYPE_CHECKING:  # pragma: no cover
    from kube_batch_tpu.framework.policy import TensorPolicy
    from kube_batch_tpu.framework.session import Session


class Arguments(dict):
    """Per-plugin config map with typed getters
    (≙ framework/arguments.go · Arguments)."""

    def get_int(self, key: str, default: int) -> int:
        return int(self.get(key, default))

    def get_float(self, key: str, default: float) -> float:
        return float(self.get(key, default))

    def get_bool(self, key: str, default: bool) -> bool:
        v = self.get(key, default)
        if isinstance(v, str):
            return v.lower() in ("1", "true", "yes", "on")
        return bool(v)


class Plugin:
    """Base plugin.

    * `register(policy, tier)` — contribute pure tensor fns (order keys,
      predicate masks, score terms, veto masks) to the TensorPolicy.
      Called once per configuration load, NOT per cycle.
    * `on_session_open` / `on_session_close` — per-cycle host hooks
      (≙ OnSessionOpen/OnSessionClose); close is where user-facing
      reporting happens (gang's unschedulable events).
    """

    name: str = "plugin"

    def __init__(self, arguments: Mapping[str, Any] | None = None) -> None:
        self.args = Arguments(arguments or {})
        self._enabled: dict[str, bool] = {}

    def set_enabled(self, enabled: Mapping[str, bool]) -> None:
        """Install the conf's per-extension-point enable flags
        (≙ conf.PluginOption's enableJobOrder/... booleans)."""
        self._enabled = dict(enabled)

    def enabled_for(self, point: str) -> bool:
        """Should this plugin register at `point` (e.g. "jobOrder",
        "preemptable")?  Defaults to enabled, like the reference."""
        return self._enabled.get(point, True)

    def register(self, policy: "TensorPolicy", tier: int) -> None:  # noqa: ARG002
        return

    def on_session_open(self, ssn: "Session") -> None:  # noqa: ARG002
        return

    def on_session_close(self, ssn: "Session") -> None:  # noqa: ARG002
        return


class Action:
    """Base action (≙ framework/interface.go · Action: Name/Initialize/
    Execute/UnInitialize).  Instances persist across cycles so their
    jitted kernels keep stable identity (compile once per shape bucket).
    """

    name: str = "action"

    def initialize(self, policy: "TensorPolicy") -> None:  # noqa: ARG002
        return

    def execute(self, ssn: "Session") -> None:
        raise NotImplementedError

    def uninitialize(self) -> None:
        return


PluginBuilder = Callable[[Mapping[str, Any] | None], Plugin]
PLUGIN_REGISTRY: dict[str, PluginBuilder] = {}
ACTION_REGISTRY: dict[str, Callable[[], Action]] = {}


def register_plugin(cls: type[Plugin]) -> type[Plugin]:
    """≙ framework/plugins.go · RegisterPluginBuilder (decorator form)."""
    PLUGIN_REGISTRY[cls.name] = cls
    return cls


def register_action(cls: type[Action]) -> type[Action]:
    """≙ framework.RegisterAction."""
    ACTION_REGISTRY[cls.name] = cls
    return cls


def ensure_registered() -> None:
    """Import the built-in plugin/action packages for their registration
    side effect.

    Callers that consult the registries (default_conf, build_policy)
    call this first so registration cannot depend on the caller's
    import graph — a consumer arriving via framework-only imports would
    otherwise silently get an EMPTY plugin set and a ~4x smaller
    compiled program (the bug that made bench.py measure a plugin-free
    policy through round 4 while the daemon ran the full one)."""
    import kube_batch_tpu.actions  # noqa: F401  registration side effect
    import kube_batch_tpu.plugins  # noqa: F401  registration side effect


def get_plugin_builder(name: str) -> PluginBuilder:
    if name not in PLUGIN_REGISTRY:
        raise KeyError(f"unknown plugin {name!r}; known: {sorted(PLUGIN_REGISTRY)}")
    return PLUGIN_REGISTRY[name]


def get_action(name: str) -> Action:
    if name not in ACTION_REGISTRY:
        raise KeyError(f"unknown action {name!r}; known: {sorted(ACTION_REGISTRY)}")
    return ACTION_REGISTRY[name]()
