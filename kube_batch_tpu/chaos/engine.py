"""The chaos scenario engine: deterministic fault-injecting simulation.

One `ChaosEngine.run()` drives the REAL scheduler end-to-end through
its production wire stack — `client.adapter.StreamBackend` +
`WatchAdapter` over a socketpair against a `faults.ChaosCluster` — for
`ticks` discrete steps.  Nothing mutates the scheduler cache directly:
every workload arrival, node vanish and completion crosses the JSON-
lines watch protocol, and every scheduling decision crosses back as a
correlated bind/evict request, exactly like `--cluster-stream`
production mode.

Tick anatomy (strictly ordered, which is what makes a threaded wire
stack deterministic)::

    1. fire this tick's faults      (sever stream / expire history /
                                     vanish node / steal lease / heal)
    2. apply this tick's workload   (trace events → cluster → watch)
    3. reconnect if the wire is down (resume-from-RV or 410 re-list —
                                     the SAME resume_session helper the
                                     CLI supervisor uses)
    4. renew the cluster-side lease (stand down the tick it is lost)
    5. quiesce ingest               (adapter caught up to cluster RV)
    6. scheduler.run_once()         (one real cycle; binds/evicts land)
    7. cluster.tick()               (kubelet: Bound → Running)
    8. quiesce + invariant check    (chaos/invariants.py)
    9. record the tick in the flight recorder

After the horizon the engine drains: completions past the horizon
still apply, no new arrivals or faults, and every admissible gang must
bind within `drain` ticks — the eventual-convergence invariant.  On
any violation the engine dumps the last `record` ticks of events and
decisions (the flight recorder) to a JSON post-mortem and reports
failure; the CLI (`python -m kube_batch_tpu.chaos`) exits non-zero.

Determinism contract: same (seed, scenario, faults, ticks) ⇒ identical
trace hash and identical final assignment.  The hash covers the input
schedule AND the per-tick decision log (binds/evicts sorted by uid —
the 16-way bind fan-out delivers in thread order, but the SET of
decisions per tick is deterministic).
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import random
import socket
import tempfile
import time

from kube_batch_tpu import metrics
from kube_batch_tpu import trace as trace_obs_mod
from kube_batch_tpu.api.resource import ResourceSpec
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.chaos.faults import ChaosCluster, FaultSpec, plan_faults
from kube_batch_tpu.chaos.invariants import InvariantChecker, Violation
from kube_batch_tpu.chaos.workload import (
    ScenarioSpec,
    apply_to_cluster,
    generate,
    trace_hash,
    write_trace,
)
from kube_batch_tpu.client.adapter import (
    StreamBackend,
    WatchAdapter,
    resume_session,
)
from kube_batch_tpu.scheduler import Scheduler

log = logging.getLogger(__name__)

LEASE_HOLDER = "chaos-engine"
LEASE_TTL = 1e9  # ticks are the only clock; only steal faults break it


class ChaosEngineError(RuntimeError):
    """The harness itself failed (quiesce timeout, dead wire) — exit 2,
    distinct from an invariant violation (exit 1)."""


# -- guardrail-fault tuning (active only when a FaultSpec enables a
#    guardrail fault; see faults.FaultSpec.guardrail_faults) -----------
#: Watchdog reference period: above a healthy tiny-shape CPU cycle
#: (a few ms), far below a slow-backend write (slow_response_s).
GUARDRAIL_WATCHDOG_PERIOD = 0.05
#: Consecutive overruns per rung / healthy cycles per recovery — small
#: so short fault windows climb and descend within a scenario, but
#: still ≥ 2 so one isolated compile spike cannot engage the ladder.
GUARDRAIL_ENGAGE_AFTER = 2
GUARDRAIL_RECOVER_AFTER = 3
#: Breaker knobs in TICK time (the breaker is clocked off
#: ChaosCluster.tick_now, so reset_after is a tick count).
GUARDRAIL_TRIP_AFTER = 5
GUARDRAIL_RESET_TICKS = 3.0
#: Wire round-trip timeout while a blackhole fault is configured: a
#: swallowed bind must fail in seconds, not the production 10 s.
BLACKHOLE_WIRE_TIMEOUT = 1.5

#: FaultSpec fields that must survive the trace round trip because
#: they change run behavior outside the inline event schedule (curse
#: decisions, Guardrails wiring, blackhole wire timeout, slow-fault
#: delay, the zombie-window size).  Written into the trace's meta
#: header; adopted on replay.
_META_FAULT_FIELDS = (
    "bind_fail_pct", "slow_at", "slow_ticks", "slow_response_s",
    "blackhole_at", "blackhole_ticks", "hbm_pressure_at",
    "leader_crash_at", "zombie_writes",
    "flaky_at", "flaky_ticks", "flaky_fail_pct", "flaky_flap_every",
    "flaky_drain_budget",
    "crash_restart_at", "crash_restarts", "crash_restart_every",
    "hbm_pin_at", "compile_bank",
    "storm_at", "storm_ticks", "storm_events",
    "device_loss_at", "device_loss_ticks", "device_loss_devices",
    "device_loss_refuse_devices",
)

# -- node-health fault tuning (active only when FaultSpec.flaky_at is
#    set; see faults.FaultSpec.health_faults) ---------------------------
#: Quarantine threshold in suspicion points: low enough that a short
#: flaky window cordons within a few ticks of refusals/flaps.
HEALTH_QUARANTINE_THRESHOLD = 3.0
#: Clean ticks per probation stage — small so cordon → probation → ok
#: completes inside the scenario's drain window.
HEALTH_PROBATION_TICKS = 4
HEALTH_PROBATION_CANARY = 2

#: Commit-pipeline drain bound per tick (wall seconds): under a
#: blackhole each queued op burns its wire timeout × retry attempts
#: before the breaker trips and the rest fail fast, so the bound must
#: cover a few serialized timeouts, not just the happy path.
COMMIT_DRAIN_TIMEOUT = 60.0

# -- crash-restart fault tuning (active only when
#    FaultSpec.crash_restart_at is set) ---------------------------------
#: Statestore compaction cadence in appended records: small, so the
#: compaction + HA mirror fire INSIDE a ~30-tick scenario.
STATESTORE_COMPACT_EVERY = 6
#: --state-max-age-cycles for the driven scheduler's restore: large
#: relative to the scenario, so in-scenario restores never stale-drop
#: (tests/test_statestore.py pins the staleness decay itself).
STATESTORE_MAX_AGE = 10_000

#: cycle-blocked-on-compile budget for the artifact-bank scenario
#: (wall seconds; the engine drives period-0 cycles, so "1 period" is
#: floored at the production default): a POST-restart cycle spending
#: longer than this inside compilation means the successor did not
#: adopt — it paid the cliff live.
COMPILE_BLOCK_BUDGET_S = 1.0


@dataclasses.dataclass
class ChaosResult:
    ok: bool
    ticks_run: int
    violations: list[Violation]
    trace_hash: str
    final_assignment: dict[str, str]   # pod uid → node
    faults: dict[str, int]
    recoveries: dict[str, int]
    converged_tick: int | None         # drain ticks until quiescent
    dump_path: str | None
    #: Guardrail observability (None unless a guardrail fault ran):
    #: max ladder rung seen, final /healthz state, breaker open/close
    #: counts, swallowed requests, HBM refusals, binds-while-open.
    guardrail: dict | None = None
    #: Commit-pipeline observability: mode, and (pipelined runs) the
    #: pipeline's own stats — max depth, order violations (must be 0),
    #: flush errors (must be 0), final depth after drain (must be 0).
    commit: dict | None = None
    #: Node-health observability (None unless the flaky fault ran):
    #: cordon/probation-failure counts, refused binds, placements that
    #: leaked onto cordoned nodes (must be 0), canary overruns (must
    #: be 0), drain evictions, final ledger states.
    health: dict | None = None
    #: Failover observability (None unless a leader-crash ran): the
    #: crashed/successor epochs, zombie-window accounting (attempted /
    #: rejected / accepted — accepted MUST be 0), the takeover
    #: reconcile summary, and the cluster's stale-rejection count.
    failover: dict | None = None
    #: Pack-path observability: the run's pack mode plus the packer's
    #: full/incremental/row-patched counters — a scenario that was
    #: supposed to exercise incremental packs but full-packed every
    #: cycle is visible here, and the pack-mode parity check reads it.
    pack: dict | None = None
    #: Device-mesh observability: the run's mesh size plus the
    #: packer's per-device H2D accounting — the mesh-parity check
    #: reads the device count to prove the dimension actually ran
    #: sharded while the trace hash stayed put.  Device-loss runs add
    #: the degradation-ladder evidence (rung reached, shift counts,
    #: refused rungs, per-window serve census).
    mesh: dict | None = None
    #: Hash of the DECISION log alone (no workload/fault events): the
    #: device-loss parity check compares it between a fault-on run and
    #: its fault-off baseline — the injected outage changes the fault
    #: schedule (hence the full trace hash) but must never change one
    #: decision (the mesh is a layout choice; degraded cycles solve
    #: bit-identically, doc/design/multichip-shard.md).
    decisions_hash: str = ""
    #: Joint-solve observability: whether KB_TPU_JOINT_SOLVE was on
    #: for the run's schedulers and whether the fused (joint) cycle
    #: actually served — the joint-parity check reads this to prove
    #: the dimension ran the one-solve program, not the per-action
    #: fallback, while the trace hash stayed put.
    joint: dict | None = None
    #: Crash-restart observability (None unless the crash_restart
    #: fault ran): per-restart restore records (pre/post quarantine
    #: states, refusal pins, breaker state, adoption source, wire
    #: writes during the restart window), the post-restart pin probe,
    #: journal counters, and whether the HA mirror landed.
    restart: dict | None = None
    #: Ingest observability: the run's ingest mode plus (batched runs)
    #: events/batches/coalesced totals across every adapter
    #: incarnation, and — event-storm runs — the emitted-storm count
    #: and the final mirror-parity verdict.
    ingest: dict | None = None
    #: Compile-artifact-bank observability (None unless the bank
    #: dimension ran): cumulative compile counters across every
    #: scheduler incarnation, the POST-restart incarnation's own
    #: counters (inline must be 0 — artifacts adopted), bank/mirror
    #: evidence, and the worst per-tick compile-blocked wall time.
    compile: dict | None = None
    #: Always-on observability (kube_batch_tpu/trace/): whether the
    #: run traced, which flight-recorder triggers auto-dumped (and at
    #: what cycle), and the span/decision-record volumes — the
    #: tracing-parity and trip-dump check scripts read this.
    trace: dict | None = None

    def summary(self) -> dict:
        return {
            "ok": self.ok,
            "ticks": self.ticks_run,
            "violations": [v.as_dict() for v in self.violations],
            "trace_hash": self.trace_hash,
            "decisions_hash": self.decisions_hash,
            "bound_pods": len(self.final_assignment),
            "faults": dict(self.faults),
            "recoveries": dict(self.recoveries),
            "converged_after_drain_ticks": self.converged_tick,
            "flight_recorder": self.dump_path,
            "guardrail": self.guardrail,
            "commit": self.commit,
            "failover": self.failover,
            "health": self.health,
            "pack": self.pack,
            "mesh": self.mesh,
            "joint": self.joint,
            "restart": self.restart,
            "ingest": self.ingest,
            "trace": self.trace,
            "compile": self.compile,
        }


class FlightRecorder:
    """Bounded ring of per-tick records; dumped as a JSON post-mortem
    the moment an invariant fails."""

    def __init__(self, keep: int = 64) -> None:
        self._ring: collections.deque = collections.deque(maxlen=keep)

    def record(self, entry: dict) -> None:
        self._ring.append(entry)

    def dump(self, path: str, meta: dict) -> str:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(
                {"meta": meta, "ticks": list(self._ring)},
                f, indent=1, sort_keys=True,
            )
            f.write("\n")
        return path


class ChaosEngine:
    def __init__(
        self,
        seed: int = 0,
        ticks: int = 200,
        scenario: ScenarioSpec | None = None,
        faults: FaultSpec | None = None,
        events: list[dict] | None = None,
        conf_path: str | None = None,
        record: int = 64,
        drain: int = 80,
        trace_path: str | None = None,
        dump_dir: str | None = None,
        corrupt_tick: int | None = None,
        quiesce_timeout: float = 30.0,
        wire_timeout: float | None = None,
        wire_commit: str | None = None,
        pack_mode: str | None = None,
        state_dir: str | None = None,
        ingest_mode: str | None = None,
        trace_obs: str | None = None,
        compile_bank: str | None = None,
        mesh_devices: int | str | None = None,
    ) -> None:
        self.seed = seed
        self.ticks = ticks
        self.scenario = scenario or ScenarioSpec()
        self._preset_events = events   # a replayed trace, if any
        # The pipelined dimension changes RUN behavior (commit flushes
        # off-thread between run_once and the tick barrier), so like
        # the guardrail windows it rides the trace meta header and is
        # adopted on replay unless the caller overrides explicitly.
        if wire_commit is None and events is not None:
            meta = next(
                (e for e in events if e.get("op") == "meta"), None
            )
            if meta is not None:
                wire_commit = meta.get("wire_commit")
        self.wire_commit = wire_commit or "sync"
        if self.wire_commit not in ("sync", "pipelined"):
            raise ValueError(
                f"wire_commit must be 'sync' or 'pipelined', got "
                f"{self.wire_commit!r}"
            )
        # The pack-mode dimension (incremental row-patched packs vs a
        # full rebuild every cycle) must be decision-invisible: device
        # state is bit-identical either way, so the SAME seed must
        # produce the SAME trace hash under both — `make chaos` pins
        # it.  Like wire_commit it rides the meta header (excluded
        # from the hash) and is adopted on replay unless overridden.
        if pack_mode is None and events is not None:
            meta = next(
                (e for e in events if e.get("op") == "meta"), None
            )
            if meta is not None:
                pack_mode = meta.get("pack_mode")
        self.pack_mode = pack_mode or "incremental"
        if self.pack_mode not in ("incremental", "full"):
            raise ValueError(
                f"pack_mode must be 'incremental' or 'full', got "
                f"{self.pack_mode!r}"
            )
        # The ingest-mode dimension (batched coalesced apply vs the
        # per-event baseline) must be decision-invisible like pack
        # mode: same seed ⇒ same trace hash under both — `make chaos`
        # pins it for the guardrail/failover/flaky/restart scenarios.
        # Rides the meta header (excluded from the hash), adopted on
        # replay unless overridden.
        if ingest_mode is None and events is not None:
            meta = next(
                (e for e in events if e.get("op") == "meta"), None
            )
            if meta is not None:
                ingest_mode = meta.get("ingest_mode")
        from kube_batch_tpu.client.adapter import resolve_ingest_mode

        self.ingest_mode = resolve_ingest_mode(ingest_mode)
        # The mesh dimension (node-axis sharded pack/solve across N
        # devices vs the single-device path) must be decision-
        # invisible exactly like pack mode: the sharded solve is
        # bit-identical, so the SAME seed must produce the SAME trace
        # hash at any device count — `make chaos` pins 1 vs 8.  Rides
        # the meta header (excluded from the hash), adopted on replay
        # unless overridden.
        if mesh_devices is None and events is not None:
            meta = next(
                (e for e in events if e.get("op") == "meta"), None
            )
            if meta is not None:
                mesh_devices = meta.get("mesh_devices")
        from kube_batch_tpu.parallel.mesh import resolve_mesh_devices

        self.mesh_devices = resolve_mesh_devices(mesh_devices)
        #: Ingest observability accumulated across every adapter
        #: incarnation (reconnects/restarts replace the adapter).
        self._ingest_stats = {"events": 0, "batches": 0, "coalesced": 0}
        # The always-on observability dimension (kube_batch_tpu/trace/):
        # scenarios run with tracing ON by default — the production
        # default — and the tracing-parity tests pin that the SAME
        # seed hashes identically with it off (tracing is recording,
        # never a decision input, so it must be invisible to the
        # hashed schedule).  Deliberately NOT in the trace meta header:
        # replay parity across the dimension is exactly what the
        # parity tests assert.
        self.trace_obs = trace_obs or "on"
        if self.trace_obs not in ("on", "off"):
            raise ValueError(
                f"trace_obs must be 'on' or 'off', got {self.trace_obs!r}"
            )
        self._trace_dump_dir: str | None = None
        self._trace_summary: dict | None = None
        #: tick -> flight-recorder auto-dump count at END of tick; the
        #: breaker-trip invariant asserts the dump landed ON the trip
        #: tick, not eventually.
        self._trace_dumps_by_tick: dict[int, int] = {}
        self.commit = None  # CommitPipeline, created in run()
        if faults is None and events is not None:
            # A recorded trace carries the recording's run-time fault
            # parameters in its "meta" header line; adopt them unless
            # the caller overrides explicitly.  Planned faults (drops,
            # gaps, vanishes, steals) ride inline as events, but the
            # fields below change RUN behavior, not the schedule:
            # bind_fail_pct is a fire-time curse decision, and the
            # guardrail windows decide whether a Guardrails instance
            # (breaker, watchdog, ceiling) is wired at all plus the
            # blackhole wire timeout — without them a replayed
            # guardrail trace would apply the inline blackhole/slow
            # events against an unguarded scheduler and diverge.
            meta = next(
                (e for e in events if e.get("op") == "meta"), None
            )
            if meta is not None:
                faults = FaultSpec(**{
                    k: meta[k] for k in _META_FAULT_FIELDS if k in meta
                })
        self.faults = faults or FaultSpec()
        self.conf_path = conf_path
        self.drain = drain
        self.trace_path = trace_path
        self.dump_dir = dump_dir or tempfile.gettempdir()
        self.corrupt_tick = corrupt_tick
        self.quiesce_timeout = quiesce_timeout
        self.recorder = FlightRecorder(keep=record)
        self.fault_counts: collections.Counter = collections.Counter()
        self.recovery_counts: collections.Counter = collections.Counter()
        # Resolved-at-fire-time fault state.
        self._vanish_rng = random.Random(f"chaos-vanish-{seed}")
        self._healable: collections.deque = collections.deque()
        self._pending_gap = False
        self._have_lease = False
        self._lease_lost = False
        # -- leadership fencing state (leader-crash fault) -------------
        self._holder = LEASE_HOLDER          # current elector identity
        self._epoch: int | None = None       # current fencing epoch
        self._incarnation = 0                # bumped per leader-crash
        self._zombie_attempted = 0
        self._zombie_accepted = 0            # MUST stay 0 (invariant)
        self._crash_epochs: tuple[int, int] | None = None  # (old, new)
        self._reconcile_summary: dict | None = None
        self._forged: dict | None = None     # forged BINDING census
        # -- crash-restart state (statestore fault) --------------------
        # A restart scenario journals the driven scheduler's
        # operational state to a real StateStore in `state_dir`
        # (auto-created temp dir unless the caller pins one — the
        # cold-start/corrupt-journal parity tests do) and re-adopts it
        # on every crash-restart.
        self.state_dir = state_dir
        #: Auto-created (and teardown-removed) lazily in
        #: _build_statestore — an engine constructed but never run
        #: must not leave an empty temp dir behind.
        self._state_dir_owned = False
        self.statestore = None
        self._restarts: list[dict] = []
        # -- AOT compile-artifact bank dimension -----------------------
        # (doc/design/compile-artifacts.md) Resolved from the fault
        # spec (scenario JSON / replayed meta header) with a CLI
        # override (`--compile-bank off` is the decision-invisibility
        # parity run: the same seed must hash identically with the
        # bank on and off — adopting an artifact and compiling it
        # fresh are the same program).
        if compile_bank not in (None, "auto", "on", "off"):
            raise ValueError(
                f"compile_bank must be auto/on/off, got {compile_bank!r}"
            )
        if compile_bank == "on":
            self.compile_bank_mode = self.faults.compile_bank or 1
        elif compile_bank == "off":
            self.compile_bank_mode = 0
        else:
            self.compile_bank_mode = self.faults.compile_bank
        self.compile_bank = None   # ArtifactBank, built in run()
        #: Cumulative compile-path evidence across every scheduler
        #: incarnation (harvested at each crash + at the end).
        self._compile_totals: collections.Counter = collections.Counter()
        #: Final (post-last-restart) incarnation's compile_stats.
        self._compile_final: dict | None = None
        #: tick -> seconds that tick's cycle spent blocked on
        #: compilation (the cycle-blocked-on-compile invariant).
        self._compile_wait_by_tick: dict[int, float] = {}
        #: Persistent HBM-pin fault state: the ceiling settled between
        #: the serving and the refused projection (survives restarts
        #: via engine config, like the CLI's --hbm-ceiling-mb), and
        #: the canonical shapes of the durably-pinned program.
        self._pinned_ceiling: int | None = None
        self._pinned_shapes: tuple | None = None
        self._pin_probe: dict | None = None
        # -- node-health state (flaky-node fault) ----------------------
        # The flaky fault drives the scheduler with a NodeHealthLedger
        # (clocked in cycles == ticks, deterministic) AND a Guardrails
        # instance: the breaker must be LIVE so the run asserts that a
        # flaky node's answered refusals never trip it.  Restart
        # scenarios build both too — they are the state under test.
        self._flaky_victim: str | None = None
        self._health_by_tick: dict[int, dict] = {}
        # -- device-loss state (mesh degradation ladder) ---------------
        #: The live injector (raises DeviceLossError at the solve
        #: seam while the topology is wider than the healthy floor);
        #: kept on the engine so a crash-restart mid-window re-arms
        #: the successor incarnation.
        self._device_loss_injector = None
        #: tick -> ladder sample at end of a COMPLETED run_once (rung,
        #: devices, refused rungs): the no-cycle-lost-while-degraded
        #: invariant reads window-tick coverage; NOT part of the
        #: trace hash (the ladder's walk is observability, the
        #: decisions are the contract).
        self._mesh_by_tick: dict[int, dict] = {}
        self._cordoned_placements = 0
        self._canary_overruns = 0
        self.health = self._build_health()
        # Guardrail wiring: any guardrail fault in the spec makes the
        # driven scheduler carry a Guardrails instance, its breaker
        # clocked off the TICK counter (reset windows count ticks, not
        # wall seconds — same-seed runs stay reproducible).  Health
        # and restart faults wire one too (see above).
        self.guardrails = self._build_guardrails()
        if wire_timeout is None:
            wire_timeout = (
                BLACKHOLE_WIRE_TIMEOUT if self.faults.blackhole_at
                else 10.0
            )
        self.wire_timeout = wire_timeout
        #: tick -> breaker state at END of tick (guardrail runs only);
        #: the breaker-open invariant reads consecutive "open" pairs.
        self._breaker_by_tick: dict[int, str] = {}
        self.scheduler: Scheduler | None = None
        # Live wire state.
        self.cluster: ChaosCluster | None = None
        self.backend: StreamBackend | None = None
        self.adapter: WatchAdapter | None = None
        self.cache: SchedulerCache | None = None
        self._socks: list[socket.socket] = []
        self._cluster_sock: socket.socket | None = None
        self._sched_sock: socket.socket | None = None
        self._decision_cursor = 0
        # Decision log folded into the trace hash (sorted per tick).
        self._decisions: list[dict] = []

    # -- wiring ---------------------------------------------------------
    def _build_health(self):
        """A fresh NodeHealthLedger for the driven scheduler (or None)
        — called at boot AND by every crash-restart: the ledger object
        dies with the 'process'; the statestore is what carries its
        memory across."""
        if not (self.faults.health_faults or self.faults.restart_faults):
            return None
        from kube_batch_tpu.health import NodeHealthConfig, NodeHealthLedger

        return NodeHealthLedger(NodeHealthConfig(
            quarantine_threshold=HEALTH_QUARANTINE_THRESHOLD,
            probation_ticks=HEALTH_PROBATION_TICKS,
            probation_canary=HEALTH_PROBATION_CANARY,
            drain_cordoned=self.faults.flaky_drain_budget > 0,
            drain_budget=self.faults.flaky_drain_budget,
        ))

    def _build_guardrails(self):
        """A fresh Guardrails instance (or None) — same rebuild-at-
        restart contract as `_build_health`.  The hbm-pin fault's
        settled ceiling re-applies like the CLI's --hbm-ceiling-mb
        flag would on a real restart (configuration survives; the PIN
        must come back from the statestore)."""
        if not (
            self.faults.guardrail_faults
            or self.faults.health_faults
            or self.faults.restart_faults
            or self.faults.ingest_faults
            or self.faults.device_loss_faults
        ):
            return None
        from kube_batch_tpu.guardrails import GuardrailConfig, Guardrails

        rails = Guardrails(GuardrailConfig(
            hbm_ceiling_mb=None,
            watchdog_overruns=GUARDRAIL_ENGAGE_AFTER,
            watchdog_recovery=GUARDRAIL_RECOVER_AFTER,
            watchdog_period=GUARDRAIL_WATCHDOG_PERIOD,
            breaker_failures=GUARDRAIL_TRIP_AFTER,
            breaker_reset_s=GUARDRAIL_RESET_TICKS,
            backoff_base_s=0.01,
            backoff_cap_s=0.04,
            backoff_attempts=2,
        ))
        if self._pinned_ceiling is not None:
            rails.hbm.ceiling_bytes = int(self._pinned_ceiling)
        return rails

    def _build_commit(self) -> None:
        """The pipelined commit dimension's pipeline (no-op in sync
        mode) — at boot and after every crash-restart (a new process
        gets a new pipeline; the old one died with its workers)."""
        if self.wire_commit != "pipelined":
            return
        from kube_batch_tpu.framework.commit import (
            DEFAULT_WORKERS,
            CommitPipeline,
        )

        on_flush = None
        if self.guardrails is not None:
            on_flush = lambda s: self.guardrails.observe_flush(  # noqa: E731
                s, cache=self.cache,
            )
        workers = DEFAULT_WORKERS
        if self.faults.slow_at:
            # A slow-but-ALIVE backend serializes its delayed
            # responses, so N concurrent sends see up to N×delay of
            # queueing — clamp concurrency inside the wire timeout
            # (doc/design/pipelined-commit.md · sizing).
            workers = min(DEFAULT_WORKERS, max(1, int(
                (self.wire_timeout * 0.5)
                / max(self.faults.slow_response_s, 1e-6)
            )))
        self.commit = CommitPipeline(
            cache=self.cache, on_flush=on_flush, workers=workers,
        )
        self.cache.commit = self.commit
        if self.guardrails is not None:
            self.guardrails.attach_commit(self.commit)

    def _build_statestore(self):
        """Open (or re-open, post-restart) the journal in state_dir —
        the same path a new process on the same host would.  A restart
        scenario with no caller-pinned dir gets a temp one here,
        removed at teardown."""
        if self.state_dir is None:
            if not self.faults.restart_faults:
                return None
            self.state_dir = tempfile.mkdtemp(prefix="kb-chaos-state-")
            self._state_dir_owned = True
        from kube_batch_tpu.statestore import StateStore, journal_path

        store = StateStore(
            journal_path(self.state_dir),
            compact_every=STATESTORE_COMPACT_EVERY,
        )
        store.mirror_sink = self._mirror_state
        return store

    def _build_compile_bank(self):
        """The AOT artifact bank (or None) under the engine's state
        dir — same directory discipline as the CLI (--state-dir/
        compile_artifacts), rebuilt per incarnation like every other
        world object; the DIRECTORY is what survives a same-host
        crash.  Mode 2 (peer adoption) wipes the directory at each
        crash instead, so the successor must adopt through the wire
        mirror alone."""
        if not self.compile_bank_mode:
            return None
        if self.state_dir is None:
            self.state_dir = tempfile.mkdtemp(prefix="kb-chaos-state-")
            self._state_dir_owned = True
        from kube_batch_tpu.compile_cache import (
            ARTIFACT_DIRNAME,
            ArtifactBank,
        )

        bank = ArtifactBank(os.path.join(self.state_dir,
                                         ARTIFACT_DIRNAME),
                            mesh_devices=self.mesh_devices)
        bank.mirror_sink = self._mirror_artifact
        return bank

    def _mirror_artifact(self, payload: dict) -> None:
        """One bank entry through the live write seam
        (breaker-guarded, epoch-fenced).  Best-effort — the local
        bank holds the truth; putCompileArtifact is not a hashed
        wire-log op, so the mirror is decision-invisible."""
        seam = self.cache.binder if self.cache is not None else None
        put = getattr(seam, "put_compile_artifact", None)
        if not callable(put):
            return
        try:
            put(payload)
        except Exception as exc:  # noqa: BLE001 — re-mirrored by the
            # next put (or the successor's own compiles)
            log.debug("chaos artifact mirror failed: %s", exc)

    def _harvest_compile(self, scheduler, final: bool = False) -> None:
        """Fold one (dying or finished) incarnation's compile counters
        into the run totals; the last incarnation's stats additionally
        pin the post-restart zero-inline-compile invariant."""
        if scheduler is None or not self.compile_bank_mode:
            return
        self._compile_totals.update(scheduler.compile_stats)
        if final:
            self._compile_final = dict(scheduler.compile_stats)

    def _mirror_state(self, payload: dict) -> None:
        """The statestore's HA mirror through the live write seam
        (breaker-guarded: fails fast while open).  Best-effort — the
        journal already holds the truth.  putStateSnapshot is not a
        hashed wire-log op, so the mirror is decision-invisible."""
        seam = self.cache.binder if self.cache is not None else None
        put = getattr(seam, "put_state_snapshot", None)
        if not callable(put):
            return
        try:
            put(payload)
        except Exception as exc:  # noqa: BLE001 — re-mirrored at the
            # next compaction
            log.debug("chaos state mirror failed: %s", exc)

    def _connect(self, replay: bool) -> None:
        """One scheduler session over a fresh socketpair; the cluster
        side serves requests on its reader thread."""
        a, b = socket.socketpair()
        cl_r = a.makefile("r", encoding="utf-8")
        cl_w = a.makefile("w", encoding="utf-8")
        sch_r = b.makefile("r", encoding="utf-8")
        sch_w = b.makefile("w", encoding="utf-8")
        self.cluster.attach(cl_r, cl_w)
        if not self.cluster._started:
            self.cluster.start()
        if replay:
            self.cluster.replay(cl_w)
        old = self.adapter
        if self.backend is None:
            self.backend = StreamBackend(sch_w, timeout=self.wire_timeout)
        else:
            self.backend.reconnect(sch_w)
        adapter = WatchAdapter(self.cache, sch_r, backend=self.backend,
                               ingest_mode=self.ingest_mode)
        if old is not None:
            adapter.resource_versions.update(old.resource_versions)
            adapter.list_rv = old.list_rv
            self._harvest_ingest(old)
        adapter.start()
        self._socks.extend((a, b))
        self._cluster_sock = a
        self._sched_sock = b  # the zombie sever targets this side
        self.adapter = adapter

    def _sever_stream(self) -> None:
        """Cut the 'network' under both sides (≙ a tunnel blip)."""
        try:
            self._cluster_sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        deadline = time.monotonic() + self.quiesce_timeout
        while not self.adapter.stopped.wait(0.01):
            if time.monotonic() > deadline:
                raise ChaosEngineError("severed stream never stopped "
                                       "the watch adapter")

    def _reconnect(self) -> str:
        """Dial a fresh session and resume — the identical recovery the
        CLI supervisor runs (shared resume_session helper)."""
        since = self.adapter.latest_rv
        self._connect(replay=False)
        mode = resume_session(
            self.cache, self.backend, self.adapter, since,
            sync_timeout=self.quiesce_timeout,
        )
        self.recovery_counts[mode] += 1
        metrics.chaos_recoveries.inc(mode)
        return mode

    # -- per-tick phases ------------------------------------------------
    def _fire_fault(self, ev: dict, rec: dict) -> None:
        kind = ev["kind"]
        detail: dict = {"kind": kind}
        if kind in ("stream-drop", "watch-gap"):
            self._sever_stream()
            self._pending_gap = kind == "watch-gap"
            self.fault_counts[kind] += 1
            metrics.chaos_faults_injected.inc(kind)
        elif kind == "node-vanish":
            spec = self.cluster.vanish_node(self._vanish_rng)
            if spec is None:
                detail["skipped"] = True
            else:
                self._healable.append(spec)
                detail["node"] = spec["name"]
                self.fault_counts[kind] += 1
                metrics.chaos_faults_injected.inc(kind)
        elif kind == "node-heal":
            if self._healable:
                spec = self._healable.popleft()
                self.cluster.heal_node(spec)
                detail["node"] = spec["name"]
                self.recovery_counts["node-healed"] += 1
                metrics.chaos_recoveries.inc("node-healed")
            else:
                detail["skipped"] = True
        elif kind == "lease-steal":
            self.cluster.steal_lease()
            self.fault_counts[kind] += 1
            metrics.chaos_faults_injected.inc(kind)
        elif kind == "lease-return":
            self.cluster.return_lease()
        elif kind == "slow-backend":
            self.cluster.response_delay = self.faults.slow_response_s
            detail["delay_s"] = self.faults.slow_response_s
            self.fault_counts[kind] += 1
            metrics.chaos_faults_injected.inc(kind)
        elif kind == "slow-heal":
            self.cluster.response_delay = 0.0
            self.recovery_counts["slow-healed"] += 1
            metrics.chaos_recoveries.inc("slow-healed")
        elif kind == "bind-blackhole":
            self.cluster.blackhole = True
            self.fault_counts[kind] += 1
            metrics.chaos_faults_injected.inc(kind)
        elif kind == "blackhole-heal":
            self.cluster.blackhole = False
            self.recovery_counts["blackhole-healed"] += 1
            metrics.chaos_recoveries.inc("blackhole-healed")
        elif kind == "leader-crash":
            self._leader_crash(detail)
            self.fault_counts[kind] += 1
            metrics.chaos_faults_injected.inc(kind)
        elif kind == "crash-restart":
            self._crash_restart(detail)
            self.fault_counts[kind] += 1
            metrics.chaos_faults_injected.inc(kind)
        elif kind == "hbm-pin":
            self._fire_hbm_pin(detail)
        elif kind == "flaky-node":
            # Victim resolved at fire time from the SORTED live node
            # set — deterministic, like the vanish target.
            with self.cluster._lock:
                names = sorted(self.cluster.nodes)
            if not names:
                detail["skipped"] = True
            else:
                self._flaky_victim = names[0]
                self.cluster.set_flaky(
                    self._flaky_victim, self.faults.flaky_fail_pct,
                )
                detail["node"] = self._flaky_victim
                self.fault_counts[kind] += 1
                metrics.chaos_faults_injected.inc(kind)
        elif kind == "flaky-heal":
            self.cluster.set_flaky(None)
            self.recovery_counts["flaky-healed"] += 1
            metrics.chaos_recoveries.inc("flaky-healed")
        elif kind == "flaky-flap":
            if self._flaky_victim is None:
                detail["skipped"] = True
            else:
                self.cluster.flap_node(self._flaky_victim, down=True)
                detail["node"] = self._flaky_victim
                self.fault_counts[kind] += 1
                metrics.chaos_faults_injected.inc(kind)
        elif kind == "flaky-flap-heal":
            if self._flaky_victim is None:
                detail["skipped"] = True
            else:
                self.cluster.flap_node(self._flaky_victim, down=False)
                self.recovery_counts["flap-healed"] += 1
                metrics.chaos_recoveries.inc("flap-healed")
        elif kind == "event-storm":
            emitted = self.cluster.emit_storm(self.faults.storm_events)
            detail["events"] = emitted
            if emitted:
                self.fault_counts[kind] += 1
                metrics.chaos_faults_injected.inc(kind)
            else:
                detail["skipped"] = True
        elif kind == "device-loss":
            # Arm the solve-seam injector: every dispatch at a
            # topology wider than the healthy floor raises a
            # DeviceLossError BEFORE the program runs (no state
            # mutates), so the ladder's retry replays the identical
            # cycle at the fallback rung — decisions unchanged.
            sched = self.scheduler
            if sched is None or not sched.mesh_ladder.enabled:
                detail["skipped"] = True
            else:
                from kube_batch_tpu.guardrails.mesh import DeviceLossError

                healthy = max(1, int(self.faults.device_loss_devices))

                def _inject(s, _healthy=healthy,
                            _err=DeviceLossError):
                    if s.mesh_devices > _healthy:
                        raise _err(
                            f"chaos: injected device loss (topology "
                            f"{s.mesh_devices} > {_healthy} healthy "
                            "device(s))"
                        )

                self._device_loss_injector = _inject
                sched._mesh_fault_injector = _inject
                refuse = int(self.faults.device_loss_refuse_devices)
                if refuse:
                    # The refusal leg: while the ladder holds this
                    # rung, its compile admission runs under a 1-byte
                    # ceiling — the rung must be SKIPPED loudly, never
                    # served (hbm-pressure's clamp model).
                    sched._mesh_hbm_clamp = refuse
                    detail["refuse_devices"] = refuse
                detail["healthy_devices"] = healthy
                self.fault_counts[kind] += 1
                metrics.chaos_faults_injected.inc(kind)
        elif kind == "device-heal":
            self._device_loss_injector = None
            if self.scheduler is not None:
                self.scheduler._mesh_fault_injector = None
                self.scheduler._mesh_hbm_clamp = None
            self.recovery_counts["device-healed"] += 1
            metrics.chaos_recoveries.inc("device-healed")
        elif kind == "hbm-pressure":
            # Compile ONE next-bucket program through the real
            # compile-then-admit path under a 1-byte ceiling: the HBM
            # admission must refuse it and the serving program must
            # survive.  Needs a prior non-idle cycle (warm_grown uses
            # the last snapshot's shapes).
            verdict = None
            if self.scheduler is not None and self.guardrails is not None:
                ceiling = self.guardrails.hbm
                prev = ceiling.ceiling_bytes
                ceiling.ceiling_bytes = 1
                try:
                    verdict = self.scheduler.warm_grown()
                finally:
                    ceiling.ceiling_bytes = prev
            detail["refused"] = verdict is False
            if verdict is False:
                self.fault_counts[kind] += 1
                metrics.chaos_faults_injected.inc(kind)
            else:
                detail["skipped"] = True
        else:
            raise ChaosEngineError(f"unknown fault kind {kind!r}")
        rec.setdefault("faults", []).append(detail)

    # -- leader crash + zombie-flush window -----------------------------
    def _forge_frozen_binding(self) -> dict:
        """Recreate the crashed leader's in-memory wreckage: pods its
        commit pipeline had marked BINDING whose flush outcome the
        successor cannot know.  Two deterministic specimens — one
        whose bind DID land (the cluster holds it Bound: reconcile
        must ADOPT it) and one whose bind never landed (the cluster
        still holds it Pending: reconcile must roll it back) — picked
        from sorted cluster state, so same-seed runs forge the same
        wreckage."""
        forged = {"adopted": 0, "rolled_back": 0}
        with self.cluster._lock:
            bound = sorted(
                uid for uid, p in self.cluster.pods.items()
                if p.status in (TaskStatus.BOUND, TaskStatus.RUNNING)
                and p.node is not None
            )
            pending = sorted(
                uid for uid, p in self.cluster.pods.items()
                if p.status == TaskStatus.PENDING
            )
            nodes = sorted(self.cluster.nodes)
            landed_node = (
                self.cluster.pods[bound[0]].node if bound else None
            )
        if bound:
            # The bind landed on the wire but the ack/echo died with
            # the leader: locally the pod is frozen BINDING.
            self.cache.update_pod_status(
                bound[0], TaskStatus.BINDING, node=landed_node
            )
            forged["adopted"] += 1
        if pending and nodes:
            # The bind was enqueued but never reached the wire.
            self.cache.update_pod_status(
                pending[0], TaskStatus.BINDING, node=nodes[0]
            )
            forged["rolled_back"] += 1
        return forged

    def _zombie_window(self, zombie, detail: dict) -> None:
        """The dead incarnation's flush workers fire AFTER the
        successor leads: deterministic data-plane writes through the
        still-open old connection, stamped with the dead epoch.  Every
        one must come back StaleEpoch — an accepted zombie bind is a
        double-bind across leaders, the corruption this whole PR
        exists to prevent."""
        from kube_batch_tpu.client.adapter import StaleEpochError
        from kube_batch_tpu.client.codec import encode_pod_group

        with self.cluster._lock:
            bound = sorted(
                (uid, p.node) for uid, p in self.cluster.pods.items()
                if p.status in (TaskStatus.BOUND, TaskStatus.RUNNING)
                and p.node is not None
            )
            nodes = sorted(self.cluster.nodes)
            groups = sorted(self.cluster.groups)
        writes: list[dict] = []
        if bound and len(nodes) >= 2:
            # The nastiest zombie: re-bind an ALREADY-PLACED pod to a
            # different node (a retried flush overtaking the crash).
            uid, node = bound[0]
            other = next(n for n in nodes if n != node)
            writes.append({"verb": "bind", "pod": uid, "node": other})
        if groups:
            with self.cluster._lock:
                group = self.cluster.groups[groups[0]]
            writes.append({
                "verb": "updatePodGroup",
                "object": encode_pod_group(group),
            })
        writes = writes[: max(self.faults.zombie_writes, 0)]
        rejected = 0
        for payload in writes:
            self._zombie_attempted += 1
            try:
                zombie._call(payload)
                self._zombie_accepted += 1  # invariant violation
            except StaleEpochError:
                rejected += 1
            except Exception as exc:  # noqa: BLE001 — a dead zombie
                # wire is a harness bug (the crash keeps it open)
                raise ChaosEngineError(
                    f"zombie write failed outside the fence: {exc}"
                ) from exc
        detail["zombie"] = {
            "attempted": len(writes), "rejected": rejected,
            "accepted": self._zombie_accepted,
        }

    def _leader_crash(self, detail: dict) -> None:
        """Kill the leader mid-commit and take over as a second
        elector instance, end to end through the real wire stack:

        1. forge the crashed leader's frozen-BINDING wreckage;
        2. the lease EXPIRES cluster-side (renewals stopped) — no
           release, exactly like a real crash;
        3. the engine restarts as a fresh elector identity on a fresh
           connection; the dead incarnation's connection stays OPEN;
        4. the successor wins the lease at a strictly higher epoch;
        5. the zombie-flush window fires through the dead connection
           and must be rejected write-for-write;
        6. the successor runs the SHARED takeover reconciliation
           (client/failover.py — the same helper the CLI recontend
           path runs) and the scheduler re-arms."""
        from kube_batch_tpu.client.failover import reconcile_takeover

        zombie = self.backend
        zombie_epoch = self._epoch
        zombie_sock = self._sched_sock
        zombie_adapter = self.adapter
        self._forged = self._forge_frozen_binding()
        self.cluster.expire_lease()
        self._have_lease = False
        # Second elector instance: fresh holder, fresh connection,
        # fresh StreamBackend (NOT backend.reconnect — the zombie must
        # keep its correlation state so its flushes genuinely race).
        self._incarnation += 1
        self._holder = f"{LEASE_HOLDER}-r{self._incarnation}"
        self.backend = None
        self._connect(replay=False)
        new_epoch = self.backend.acquire_lease(self._holder, LEASE_TTL)
        self.backend.set_epoch(new_epoch)
        self._epoch = new_epoch
        self._have_lease = True
        self._crash_epochs = (int(zombie_epoch or 0), int(new_epoch))
        detail["old_epoch"], detail["new_epoch"] = self._crash_epochs
        # Rewire the cache's write seams onto the successor's backend
        # (the old seam would flush into the zombie connection).  The
        # failover scenario runs guardrail-free; combining it with
        # breaker faults would reset breaker counters here.
        seam = self.backend
        if self.guardrails is not None:
            seam = self.guardrails.guard_backend(
                self.backend, self.cache, name="chaos-wire",
                clock=lambda: float(self.cluster.tick_now),
            )
        self.cache.binder = seam
        self.cache.evictor = seam
        self.cache.status_updater = seam
        # Zombie-flush window BEFORE reconcile: the stale writes race
        # the takeover, not the recovered steady state.
        self._zombie_window(zombie, detail)
        summary = reconcile_takeover(
            self.cache, self.backend, self.adapter,
            commit=self.commit, epoch=new_epoch,
        )
        self._reconcile_summary = summary
        detail["reconcile"] = summary
        self.scheduler.on_takeover()
        self.recovery_counts["leader-takeover"] += 1
        metrics.chaos_recoveries.inc("leader-takeover")
        # Collect the corpse: sever the dead incarnation's connection.
        try:
            zombie_sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        deadline = time.monotonic() + self.quiesce_timeout
        while not zombie_adapter.stopped.wait(0.01):
            if time.monotonic() > deadline:
                raise ChaosEngineError(
                    "zombie adapter never stopped after its sever"
                )

    # -- crash-restart + durable-state adoption -------------------------
    def _crash_restart(self, detail: dict) -> None:
        """Kill and restart the scheduler PROCESS mid-quarantine /
        mid-refusal / mid-outage, reusing the leader-crash restart
        machinery end to end through the real wire stack:

        1. capture pre-crash truth (quarantine states, refusal pins,
           breaker state) for the survival invariants;
        2. the crash: lease expires un-released, the journal gets NO
           goodbye write (only end-of-cycle appends exist), the dead
           incarnation's connection is severed, and every in-memory
           world object — ledger, guardrails, commit pipeline,
           Scheduler, StateStore handle — is thrown away;
        3. the restart: fresh elector identity on a fresh connection
           wins a strictly higher epoch, fresh subsystem objects are
           built from CONFIG only, and the statestore journal is
           re-opened and ADOPTED (peer mirror as fallback) — the
           identical `adopt_state` path the CLI runs;
        4. the PR-4 takeover reconciliation relists the world and the
           scheduler re-arms.

        The survival contract this exercises: a pre-crash-cordoned
        node stays masked (zero post-restart placements), a refused
        bucket is never recompiled, and an open breaker re-opens
        WITHOUT a fresh failure streak against the same dead wire."""
        from kube_batch_tpu.client.failover import reconcile_takeover
        from kube_batch_tpu.guardrails import CircuitBreaker
        from kube_batch_tpu.statestore import adopt_state

        old_guard = self.guardrails
        old_health = self.health
        old_commit = self.commit
        old_store = self.statestore
        old_sched = self.scheduler
        old_sock = self._sched_sock
        old_adapter = self.adapter
        # (1) pre-crash truth.
        pre_states = (
            dict(old_health.sample()["states"])
            if old_health is not None else {}
        )
        pre_cordoned = sorted(
            n for n, s in pre_states.items() if s == "cordoned"
        )
        pre_pins = (
            sorted(map(str, old_sched.refusal_pin_shapes()))
            if old_sched is not None else []
        )
        pre_breaker = (
            old_guard.breaker_state() if old_guard is not None
            else CircuitBreaker.CLOSED
        )
        with self.cluster._lock:
            writes_before = sum(
                self.cluster.write_requests_by_tick.values()
            )
        # Compile-path evidence dies with the incarnation; fold it
        # into the run totals first (zero-inline is asserted on the
        # SUCCESSOR's own counters).
        self._harvest_compile(old_sched)
        # (2) the crash.
        self.cluster.expire_lease()
        self._have_lease = False
        if old_commit is not None:
            # The per-tick barrier drained it last tick; stopping the
            # workers keeps the corpse from flushing post-mortem.
            old_commit.close(timeout=5.0)
            self.commit = None
            self.cache.commit = None
        if (
            old_guard is not None
            and old_guard.breaker is not None
            and old_guard.breaker.state != CircuitBreaker.CLOSED
        ):
            # The dead breaker's quiesce hold dies with the process (a
            # real restart starts the cache's resync depth at zero);
            # the RESTORED breaker re-arms its own hold below.
            self.cache.end_resync()
        if old_store is not None and old_store._f is not None \
                and not old_store._f.closed:
            # The kernel closes a dead process's fds — raw close, no
            # final compaction, no fsync: a crash gets no goodbye.
            old_store._f.close()
        try:
            old_sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        deadline = time.monotonic() + self.quiesce_timeout
        while not old_adapter.stopped.wait(0.01):
            if time.monotonic() > deadline:
                raise ChaosEngineError(
                    "crashed incarnation's adapter never stopped"
                )
        # (3) the restart.
        self._incarnation += 1
        self._holder = f"{LEASE_HOLDER}-r{self._incarnation}"
        self.backend = None
        self._connect(replay=False)
        new_epoch = self.backend.acquire_lease(self._holder, LEASE_TTL)
        self.backend.set_epoch(new_epoch)
        self._epoch = new_epoch
        self._have_lease = True
        self.health = self._build_health()
        self.guardrails = self._build_guardrails()
        seam = self.backend
        if self.guardrails is not None:
            seam = self.guardrails.guard_backend(
                self.backend, self.cache, name="chaos-wire",
                clock=lambda: float(self.cluster.tick_now),
            )
        self.cache.binder = seam
        self.cache.evictor = seam
        self.cache.status_updater = seam
        self.cache.attach_health(self.health)
        self._build_commit()
        if self.compile_bank is not None and self.compile_bank_mode == 2:
            # Peer-adoption mode: the 'successor' runs on a DIFFERENT
            # (matching-fingerprint) host — the dead leader's local
            # bank directory is not there; only the cluster-side
            # mirror is.
            import shutil

            shutil.rmtree(self.compile_bank.dir, ignore_errors=True)
        self.compile_bank = self._build_compile_bank()
        scheduler = Scheduler(
            self.cache, conf_path=self.conf_path, schedule_period=0.0,
            guardrails=self.guardrails, health=self.health,
            pack_mode=self.pack_mode, compile_bank=self.compile_bank,
            mesh_devices=self.mesh_devices,
        )
        self.scheduler = scheduler
        if self._device_loss_injector is not None:
            # A crash mid-outage restarts INTO the outage: the dead
            # devices are still dead, so the successor gets the live
            # injector (its persisted rung keeps it off the dead mesh;
            # restore_mesh_state is the other half of that contract).
            scheduler._mesh_fault_injector = self._device_loss_injector
            if self.faults.device_loss_refuse_devices:
                scheduler._mesh_hbm_clamp = int(
                    self.faults.device_loss_refuse_devices
                )
        self.statestore = self._build_statestore()
        adopted = None
        if self.statestore is not None:
            scheduler.statestore = self.statestore
            adopted = adopt_state(
                self.statestore, backend=self.backend,
                health=self.health, guardrails=self.guardrails,
                scheduler=scheduler, max_age_cycles=STATESTORE_MAX_AGE,
            )
        artifacts_peer = 0
        if self.compile_bank is not None:
            from kube_batch_tpu.compile_cache import adopt_artifacts

            artifacts_peer = adopt_artifacts(
                self.compile_bank, self.backend
            )
        # (4) takeover reconciliation — the shared PR-4 helper.
        summary = reconcile_takeover(
            self.cache, self.backend, self.adapter,
            commit=self.commit, epoch=new_epoch,
        )
        scheduler.on_takeover()
        with self.cluster._lock:
            writes_after = sum(
                self.cluster.write_requests_by_tick.values()
            )
        post_states = (
            dict(self.health.sample()["states"])
            if self.health is not None else {}
        )
        rec = {
            "tick": self.cluster.tick_now,
            "epoch": int(new_epoch or 0),
            "source": adopted.get("source") if adopted else None,
            "pre_states": pre_states,
            "post_states": post_states,
            "pre_cordoned": pre_cordoned,
            "post_cordoned": sorted(
                n for n, s in post_states.items() if s == "cordoned"
            ),
            "pins_pre": pre_pins,
            "pins_post": sorted(
                map(str, scheduler.refusal_pin_shapes())
            ),
            "breaker_pre": pre_breaker,
            "breaker_post": (
                self.guardrails.breaker_state()
                if self.guardrails is not None
                else CircuitBreaker.CLOSED
            ),
            "wire_writes_during_restart": writes_after - writes_before,
            "artifacts_peer_adopted": artifacts_peer,
            "reconcile": summary,
        }
        self._restarts.append(rec)
        detail.update({
            k: rec[k] for k in (
                "epoch", "source", "pre_cordoned", "post_cordoned",
                "breaker_pre", "breaker_post",
            )
        })
        # Collect the corpse's sockets list entry is already handled
        # by _connect's bookkeeping; the recovery is observable.
        self.recovery_counts["crash-restart"] += 1
        metrics.chaos_recoveries.inc("crash-restart")

    def _fire_hbm_pin(self, detail: dict) -> None:
        """Establish (first firing) or probe (post-restart firing) a
        PERSISTENT HBM refusal pin.

        Establish: compile one next-bucket program through the real
        `warm_grown` compile-then-admit path under a 1-byte ceiling
        (refused + pinned), then settle the ceiling midway between the
        SERVING program's projection and the refused one — the pin
        stays valid against the live ceiling, which is the state a
        crash must carry across.

        Probe: after the last restart, `warm_grown` for the same
        growth must answer False from the RESTORED pin — without
        compiling (a recompile would show up as a fresh refusal count
        or a compiled executable at the pinned shapes)."""
        from kube_batch_tpu.guardrails.hbm import projected_device_bytes

        sched, rails = self.scheduler, self.guardrails
        if sched is None or rails is None or sched._last_snap is None:
            detail["skipped"] = True
            return
        # Grow the NODE axis: the restart scenarios run zero node
        # churn, so the workload can never legitimately cross into the
        # pinned bucket — the settled ceiling below refuses exactly
        # one program (the grown one) and admits every serving shape
        # the scenario's task/job churn produces.  On an active mesh
        # the admission ceiling is PER DEVICE and the node axis shards
        # over `devices`, so the growth must be >= devices x for the
        # grown program's per-device projection to clear the serving
        # one — a +1 bump shards away to a SMALLER footprint per
        # device (the whole point of the mesh) and leaves no gap to
        # settle a ceiling into.
        devs = max(1, int(self.mesh_devices))
        grow = {"N": int(sched._last_snap.num_nodes) * devs + 1
                if devs > 1 else int(sched._last_snap.num_nodes) + 1}
        if self._pinned_shapes is not None:
            # The probe's strong form re-runs the EXACT pinned growth:
            # warm_grown must answer False from the restored pin with
            # ZERO compile work.  Possible only while the task/job
            # buckets still match the establish-time snapshot; under
            # bucket drift the probe falls back to presence +
            # never-compiled (still the refused-bucket-never-
            # recompiled contract, minus the live warm_grown answer).
            from kube_batch_tpu.cache.packer import grown_avals

            gsnap = grown_avals(sched._last_snap, grow)
            probe_shapes = sched._pin_shapes(
                sched._shape_key(sched._cycle, gsnap)[1:]
            )
            drifted = probe_shapes != self._pinned_shapes
            refusals_before = rails.hbm.refusals
            verdict = None if drifted else sched.warm_grown(grow)
            self._pin_probe = {
                "tick": self.cluster.tick_now,
                "shape_drifted": drifted,
                "verdict": verdict,
                "pinned": self._pinned_shapes in
                sched.refusal_pin_shapes(),
                "recompiled_refusals":
                    rails.hbm.refusals - refusals_before,
                "compiled_refused_shape": any(
                    sched._pin_shapes(k[1:]) == self._pinned_shapes
                    for k in sched._compiled_shapes
                ),
            }
            detail["probe"] = self._pin_probe
            return
        ceiling = rails.hbm
        prev = ceiling.ceiling_bytes
        ceiling.ceiling_bytes = 1
        try:
            verdict = sched.warm_grown(grow)
        finally:
            ceiling.ceiling_bytes = prev
        if verdict is not False:
            detail["skipped"] = True
            return
        pins = sched.export_refusal_pins()
        pin = max(pins, key=lambda p: p["projected"])
        projected = int(pin["projected"])
        serving = 0
        for exe in sched._compiled_shapes.values():
            b = projected_device_bytes(exe)
            if b:
                serving = max(serving, int(b))
        if serving >= projected or projected < 2:
            # No gap to settle a ceiling into on this backend: the pin
            # cannot stay persistently valid — skip (the scenario
            # check script requires the establish to have fired).
            detail["skipped"] = True
            return
        # Just below the refused projection: maximum admission headroom
        # for the serving shapes' churn, refusal of exactly the pinned
        # bucket.
        settled = projected - 1
        ceiling.ceiling_bytes = settled
        self._pinned_ceiling = settled
        self._pinned_shapes = sched._pin_shapes(
            (n, tuple(s)) for n, s in pin["shapes"]
        )
        detail["pinned"] = {
            "projected": projected, "serving": serving,
            "ceiling": settled,
        }
        self.fault_counts["hbm-pin"] += 1
        metrics.chaos_faults_injected.inc("hbm-pin")

    def _maybe_force_gap(self) -> None:
        """A watch-gap fault needs the missed tail to be UNSERVABLE:
        guarantee the cluster moved past the adapter's RV (a benign
        queue re-add bumps it if this tick's workload did not), then
        expire the history ring so resume gets the 410 answer."""
        if not self._pending_gap:
            return
        self._pending_gap = False
        with self.cluster._lock:
            rv_moved = self.cluster._rv > self.adapter.latest_rv
        if not rv_moved:
            q = self.cluster.queues.get("default")
            if q is not None:
                self.cluster.add_queue(q)  # benign upsert, bumps RV
        self.cluster.expire_history()

    def _renew_lease(self, rec: dict) -> bool:
        """Synchronous per-tick renewal (the tick IS the clock).
        Returns True when this engine currently leads; a lost lease
        stands the scheduler down for the tick, re-acquiring as soon
        as the usurper lets go — deterministic, no renewal thread.
        Every acquire adopts the minted fencing epoch onto the write
        backend, so data-plane writes are epoch-stamped end to end."""
        try:
            if self._have_lease:
                self.backend.renew_lease(self._holder, LEASE_TTL)
            else:
                self._epoch = self.backend.acquire_lease(
                    self._holder, LEASE_TTL
                )
                self.backend.set_epoch(self._epoch)
                self._have_lease = True
                if self._lease_lost:
                    self._lease_lost = False
                    rec["lease"] = "reacquired"
                    self.recovery_counts["lease-reacquired"] += 1
                    metrics.chaos_recoveries.inc("lease-reacquired")
        except RuntimeError:
            rec["lease"] = "lost" if self._have_lease else "contended"
            self._have_lease = False
            self._lease_lost = True
            return False
        except (ConnectionError, TimeoutError) as exc:
            raise ChaosEngineError(f"lease verb failed on a live "
                                   f"stream: {exc}") from exc
        return True

    def _quiesce(self) -> None:
        """Block until the adapter ingested everything the cluster
        emitted — the determinism barrier between phases."""
        deadline = time.monotonic() + self.quiesce_timeout
        while time.monotonic() < deadline:
            if self.adapter.stopped.is_set():
                return  # wire is down; next tick's reconnect handles it
            with self.cluster._lock:
                rv = self.cluster._rv
            if self.adapter.synced.is_set() and \
                    self.adapter.latest_rv >= rv:
                return
            time.sleep(0.002)
        raise ChaosEngineError("ingest quiesce timed out")

    def _drain_decisions(self, rec: dict) -> None:
        """Fold this tick's wire-log tail into the recorder + hash
        (sorted: the bind fan-out's thread order is not semantic)."""
        with self.cluster._lock:
            tail = self.cluster.wire_log[self._decision_cursor:]
            self._decision_cursor = len(self.cluster.wire_log)
        tail = sorted(
            tail, key=lambda e: (e["op"], e.get("uid") or "",
                                 e.get("node") or ""),
        )
        if tail:
            rec["decisions"] = tail
            self._decisions.extend(tail)
        injected = sum(1 for e in tail if e["op"] == "bind-fault")
        if injected:
            self.fault_counts["bind-fault"] += injected
            metrics.chaos_faults_injected.inc(
                "bind-fault", by=float(injected)
            )
        flaky = sum(1 for e in tail if e["op"] == "flaky-bind-fault")
        if flaky:
            self.fault_counts["flaky-bind-fault"] += flaky
            metrics.chaos_faults_injected.inc(
                "flaky-bind-fault", by=float(flaky)
            )

    # -- the run --------------------------------------------------------
    def run(self) -> ChaosResult:
        if self._preset_events is not None:
            # A replayed trace carries its fault schedule inline and its
            # run-time parameters in the meta header (consumed by
            # __init__, excluded from the hashable schedule below).
            events = [
                e for e in self._preset_events
                if e["op"] not in ("fault", "meta")
            ]
            fault_events = [
                e for e in self._preset_events if e["op"] == "fault"
            ]
        else:
            events = generate(self.scenario, self.seed, self.ticks)
            fault_events = plan_faults(self.faults, self.seed, self.ticks)
        by_tick: dict[int, list[dict]] = collections.defaultdict(list)
        for ev in events:
            by_tick[ev["tick"]].append(ev)
        faults_by_tick: dict[int, list[dict]] = collections.defaultdict(list)
        for ev in fault_events:
            faults_by_tick[ev["tick"]].append(ev)
        if self.trace_path:
            # The header makes a recorded trace self-describing: replay
            # recovers the seed (vanish-target + curse decisions are
            # resolved from it at fire time) and every behavior-bearing
            # fault field without the operator re-passing them.
            header = {
                "tick": -1, "op": "meta", "seed": self.seed,
                "wire_commit": self.wire_commit,
                "pack_mode": self.pack_mode,
                "ingest_mode": self.ingest_mode,
                "mesh_devices": self.mesh_devices,
                **{k: getattr(self.faults, k)
                   for k in _META_FAULT_FIELDS},
            }
            write_trace(self.trace_path, [header] + events + fault_events)

        # Always-on observability: ON is the production default; the
        # engine owns a temp dump dir (removed at teardown — repeated
        # chaos/CI runs must not accumulate post-mortems in /tmp).
        if self.trace_obs == "on":
            self._trace_dump_dir = tempfile.mkdtemp(
                prefix="kb-chaos-trace-"
            )
            trace_obs_mod.enable(dump_dir=self._trace_dump_dir)
        else:
            trace_obs_mod.disable()

        self.cluster = ChaosCluster(
            seed=self.seed, bind_fail_pct=self.faults.bind_fail_pct,
            history=4096,
        )
        self.cache = SchedulerCache(
            spec=ResourceSpec(),
            binder=None, evictor=None, status_updater=None,
        )
        self._connect(replay=True)
        # The backend exists only after _connect; wire the seams now.
        # With guardrail faults the write seams go through the retry +
        # breaker wrapper — exactly the production CLI wiring, with
        # the breaker clocked off ticks instead of wall seconds.  The
        # engine's OWN verbs (lease renewal, watch resume) keep using
        # the raw backend: GuardedBackend protects the scheduler's
        # write path, not the harness.
        if self.guardrails is not None:
            seam = self.guardrails.guard_backend(
                self.backend, self.cache, name="chaos-wire",
                clock=lambda: float(self.cluster.tick_now),
            )
        else:
            seam = self.backend
        self.cache.binder = seam
        self.cache.evictor = seam
        self.cache.status_updater = seam
        # The pipelined dimension: binds/status writes flush on the
        # commit pipeline between run_once and each tick's drain
        # barrier — the overlap is real (concurrent flush against the
        # live wire stack), the barrier keeps same-seed ⇒ same-hash
        # (the decision log is drained per tick with the pipeline
        # empty, and the logged binds ARE the commit acks).
        self._build_commit()
        if not self.adapter.wait_for_sync(self.quiesce_timeout):
            raise ChaosEngineError("initial LIST replay never synced")
        self.compile_bank = self._build_compile_bank()
        scheduler = Scheduler(
            self.cache, conf_path=self.conf_path, schedule_period=0.0,
            guardrails=self.guardrails, health=self.health,
            pack_mode=self.pack_mode, compile_bank=self.compile_bank,
            mesh_devices=self.mesh_devices,
        )
        self.scheduler = scheduler
        # Durable operational memory: journal end-of-cycle state and
        # adopt whatever a pre-seeded state_dir holds (a cold dir and
        # a corrupt journal must behave exactly like no statestore at
        # all — the parity acceptance criterion).
        self.statestore = self._build_statestore()
        if self.statestore is not None:
            from kube_batch_tpu.statestore import adopt_state

            scheduler.statestore = self.statestore
            adopt_state(
                self.statestore, backend=self.backend,
                health=self.health, guardrails=self.guardrails,
                scheduler=scheduler, max_age_cycles=STATESTORE_MAX_AGE,
            )
        if self.compile_bank is not None:
            from kube_batch_tpu.compile_cache import adopt_artifacts

            adopt_artifacts(self.compile_bank, self.backend)
        checker = InvariantChecker(self.cluster)
        metrics.chaos_convergence_ticks.set(-1.0)

        violations: list[Violation] = []
        converged_tick: int | None = None
        ticks_run = 0

        def one_tick(t: int, active: bool) -> list[Violation]:
            """active=False is the drain phase: completions only."""
            nonlocal ticks_run
            self.cluster.tick_now = t
            rec: dict = {"tick": t}
            if active:
                for fe in faults_by_tick.get(t, ()):
                    self._fire_fault(fe, rec)
            evs = by_tick.get(t, ())
            if not active:
                evs = [e for e in evs if e["op"] == "complete"]
            for ev in evs:
                apply_to_cluster(self.cluster, ev)
            rec["workload"] = len(evs)
            self._maybe_force_gap()
            if self.adapter.stopped.is_set() or \
                    self.backend.closed.is_set():
                rec["reconnect"] = self._reconnect()
            lead = self._renew_lease(rec)
            self._quiesce()
            if self.adapter.stopped.is_set():
                rec["reconnect"] = self._reconnect()
                self._quiesce()
            if lead:
                # Via self: a crash-restart fault replaces the
                # Scheduler (and its ledger/guardrails/statestore)
                # mid-run — the loop must drive the live incarnation,
                # not the closure-captured corpse.
                self.scheduler.run_once()
                if self.commit is not None:
                    # Tick barrier: every commit enqueued this cycle
                    # must land (or fail into resync) before the
                    # kubelet tick and the invariant check — the
                    # determinism boundary of the pipelined dimension.
                    # With the breaker open the queue fails fast, so a
                    # timeout here is a harness failure, not a slow
                    # wire.
                    if not self.commit.drain(COMMIT_DRAIN_TIMEOUT):
                        raise ChaosEngineError(
                            "commit pipeline never drained at the "
                            f"tick barrier (depth {self.commit.depth})"
                        )
                # Re-journal AFTER the barrier: a breaker trip landing
                # during the flush drain postdates run_once's own
                # append, and a crash fault next tick must find it.
                self.scheduler.journal_state()
                if self.compile_bank_mode:
                    # Per-tick compile evidence: the wall seconds this
                    # cycle spent blocked on compilation (the
                    # cycle-blocked-on-compile invariant) + the live
                    # counters for the recorder.  NOT part of the
                    # trace hash.
                    self._compile_wait_by_tick[t] = \
                        self.scheduler._last_compile_wait_s
                    rec["compile"] = dict(self.scheduler.compile_stats)
                if self.faults.device_loss_faults:
                    # A sample landing here means run_once COMPLETED —
                    # the coverage the no-cycle-lost-while-degraded
                    # invariant reads.  NOT part of the trace hash.
                    lad = self.scheduler.mesh_ladder
                    self._mesh_by_tick[t] = {
                        "rung": lad.rung,
                        "devices": lad.devices,
                        "refused": sorted(lad._refused),
                    }
                    rec["mesh"] = dict(self._mesh_by_tick[t])
            else:
                rec["stood-down"] = True
            if self.corrupt_tick is not None and t == self.corrupt_tick:
                if self.cluster.force_double_bind():
                    rec["corruption"] = "forced-double-bind"
            self.cluster.tick()
            self._quiesce()
            self._drain_decisions(rec)
            if self.guardrails is not None:
                # Sampled at end-of-tick for the recorder AND the
                # breaker-open invariant; NOT part of the trace hash
                # (rung transitions depend on wall latency).
                state = self.guardrails.breaker_state()
                self._breaker_by_tick[t] = state
                rec["guardrail"] = {
                    "state": self.guardrails.state,
                    "breaker": state,
                }
            tracer = trace_obs_mod.get()
            if tracer is not None:
                # End-of-tick auto-dump census: the breaker-trip
                # invariant asserts the post-mortem landed ON the trip
                # tick.  NOT part of the trace hash.
                self._trace_dumps_by_tick[t] = \
                    len(tracer.recorder.dumps)
            if self.health is not None:
                # End-of-tick ledger sample: feeds the recorder and
                # the per-tick health invariants (a tick is "fully
                # cordoned" for a node when both its boundaries say
                # so, same convention as the breaker-open window).
                # NOT part of the trace hash.
                self._health_by_tick[t] = self.health.sample()
                rec["health"] = {
                    "states": self._health_by_tick[t]["states"],
                    "cordons": self._health_by_tick[t]["cordons_total"],
                }
            found = checker.check_tick(t)
            if self.health is not None:
                found = found + self._check_health_tick(
                    t, rec.get("decisions", ())
                )
            if found:
                rec["violations"] = [v.as_dict() for v in found]
                for v in found:
                    metrics.chaos_invariant_violations.inc(v.kind)
            self.recorder.record(rec)
            ticks_run += 1
            return found

        try:
            for t in range(self.ticks):
                violations = one_tick(t, active=True)
                if violations:
                    break
            else:
                # Convergence drain: no new arrivals or faults; late
                # completions keep applying (they free the capacity a
                # backlog is waiting on); every admissible gang must
                # bind before the deadline.
                for extra in range(self.drain):
                    t = self.ticks + extra
                    violations = one_tick(t, active=False)
                    if violations:
                        break
                    if self._all_settled() and self._rails_recovered() \
                            and self._health_recovered() \
                            and self._mesh_recovered():
                        # Guardrail runs also drain until the ladder
                        # descends and the breaker closes; health runs
                        # until every quarantined node re-admitted
                        # through probation: "converged" means the
                        # workload settled AND the daemon is back to
                        # full service on full capacity.
                        converged_tick = extra
                        metrics.chaos_convergence_ticks.set(float(extra))
                        break
                else:
                    violations = checker.pending_after_deadline(
                        self.ticks + self.drain
                    )
                if not violations and self.faults.guardrail_faults:
                    violations = self._check_guardrails(ticks_run)
                if not violations and self.commit is not None:
                    violations = self._check_commit(ticks_run)
                if not violations and self.faults.leader_crash_at:
                    violations = self._check_failover(ticks_run)
                if not violations and self.faults.health_faults:
                    violations = self._check_flaky(ticks_run)
                if not violations and self.faults.restart_faults:
                    violations = self._check_restart(ticks_run)
                if not violations and self.faults.ingest_faults:
                    violations = self._check_ingest(ticks_run)
                if not violations and self.faults.device_loss_faults:
                    violations = self._check_mesh_ladder(ticks_run)
                if not violations and self.compile_bank_mode:
                    violations = self._check_compile(ticks_run)
        finally:
            self._teardown()

        # Recovery bookkeeping the cluster tracked itself.
        if self.cluster.recovered_binds:
            self.recovery_counts["bind-retried"] = \
                self.cluster.recovered_binds
            metrics.chaos_recoveries.inc(
                "bind-retried", by=float(self.cluster.recovered_binds)
            )

        final = self._final_assignment()
        full_hash = trace_hash(
            events + fault_events + self._decisions
        )
        decisions_hash = trace_hash(self._decisions)
        dump_path = None
        if violations:
            os.makedirs(self.dump_dir, exist_ok=True)
            dump_path = os.path.join(
                self.dump_dir,
                f"chaos-flight-seed{self.seed}.json",
            )
            self.recorder.dump(dump_path, meta={
                "seed": self.seed,
                "ticks": ticks_run,
                "violations": [v.as_dict() for v in violations],
                "trace_hash": full_hash,
            })
            log.error(
                "chaos: %d invariant violation(s); flight recorder "
                "dumped to %s", len(violations), dump_path,
            )
        return ChaosResult(
            ok=not violations,
            ticks_run=ticks_run,
            violations=list(violations),
            trace_hash=full_hash,
            decisions_hash=decisions_hash,
            final_assignment=final,
            faults=dict(self.fault_counts),
            recoveries=dict(self.recovery_counts),
            converged_tick=converged_tick,
            dump_path=dump_path,
            guardrail=self._guardrail_summary(),
            commit=self._commit_summary(),
            failover=self._failover_summary(),
            health=self._health_summary(),
            pack=self._pack_summary(),
            mesh=self._mesh_summary(),
            joint=self._joint_summary(),
            restart=self._restart_summary(),
            ingest=self._ingest_summary(),
            trace=self._trace_summary,
            compile=self._compile_summary(),
        )

    def _pack_summary(self) -> dict | None:
        packer = getattr(
            getattr(self, "scheduler", None), "packer", None
        )
        if packer is None:
            return None
        return {
            "mode": self.pack_mode,
            "full_packs": packer.full_packs,
            "incremental_packs": packer.incremental_packs,
            "row_patched_packs": packer.row_patched_packs,
            "fallback_reasons": dict(packer.fallback_reasons),
        }

    def _mesh_summary(self) -> dict | None:
        scheduler = getattr(self, "scheduler", None)
        if scheduler is None:
            return None
        packer = getattr(scheduler, "packer", None)
        out = {
            "devices": self.mesh_devices,
            "active": bool(getattr(scheduler.mesh, "active", False)),
            "last_h2d_bytes_per_device": (
                getattr(packer, "last_h2d_bytes_per_device", 0)
                if packer is not None else 0
            ),
        }
        if self.faults.device_loss_faults:
            # Degradation-ladder evidence for check_chaos_mesh.py:
            # the ladder must have engaged (≥1 down-shift), every
            # window tick must have served, a clamped rung must show
            # in the refused census, and the run must end healed.
            lad = scheduler.mesh_ladder
            w0 = self.faults.device_loss_at
            w1 = w0 + self.faults.device_loss_ticks
            window = [
                t for t in range(w0, min(w1, self.ticks))
            ]
            out["ladder"] = {
                "chain": list(lad.chain),
                "rung": lad.rung,
                "live_devices": lad.devices,
                "max_rung_seen": lad.max_rung_seen,
                "transitions": lad.transitions,
                "refused_rungs": sorted(
                    {d for s in self._mesh_by_tick.values()
                     for d in s.get("refused", ())}
                ),
                "window_ticks": len(window),
                "window_served": sum(
                    1 for t in window if t in self._mesh_by_tick
                ),
                "window_degraded": sum(
                    1 for t in window
                    if self._mesh_by_tick.get(t, {}).get("rung", 0) > 0
                ),
                "shifts_down": metrics.mesh_rung_shifts.value("down"),
                "shifts_up": metrics.mesh_rung_shifts.value("up"),
                "solve_failures_device":
                    metrics.mesh_solve_failures.value("device"),
            }
        return out

    def _joint_summary(self) -> dict | None:
        scheduler = getattr(self, "scheduler", None)
        if scheduler is None:
            return None
        return {
            "enabled": bool(getattr(scheduler, "_joint_solve", False)),
            # the joint builder refuses custom actions with a
            # ValueError, which lands the daemon on the per-action
            # fallback (_cycle is None) — a parity run that silently
            # fell back proves nothing, so record the cycle presence
            "fused_cycle": getattr(scheduler, "_cycle", None) is not None,
        }

    # -- guardrail invariants ------------------------------------------
    def _rails_recovered(self) -> bool:
        """Full service restored: breaker not open, and — only when
        the slow fault actually exercises the ladder — rung 0.  The
        rung is WALL-clocked (a cold process's compile spikes overrun
        the 50 ms reference period; a warm one's don't), so gating
        convergence on it in scenarios that never inject slowness
        would make the drain length — and with it the drain ticks'
        pod-gone log entries, hence the trace hash — depend on compile
        cache warmth instead of the seed."""
        if self.guardrails is None:
            return True
        from kube_batch_tpu.guardrails import CircuitBreaker

        rung_recovered = (
            self.guardrails.rung == 0 if self.faults.slow_at else True
        )
        return (
            rung_recovered
            and self.guardrails.breaker_state() != CircuitBreaker.OPEN
        )

    def _mesh_recovered(self) -> bool:
        """Drain gate for device-loss runs: 'converged' includes the
        ladder back at rung 0 — the heal-after-restore half of the
        contract (canary streaks climbing through admitted rungs after
        the fault window closes).  Non-device-loss runs gate on
        nothing: the rung can only move when the injector is armed."""
        if not self.faults.device_loss_faults or self.scheduler is None:
            return True
        return self.scheduler.mesh_ladder.rung == 0

    def _check_mesh_ladder(self, tick: int) -> list[Violation]:
        """Post-run assertions for the device-loss scenario
        (guardrails/mesh.py):

        * **mesh-ladder-unarmed** — the fault ran against a 1-device
          scheduler (no chain to walk): the run proves nothing;
        * **mesh-ladder-never-engaged** — the injected window never
          moved the ladder off rung 0;
        * **mesh-cycle-lost** — a window tick never completed its
          cycle: the ladder's whole point is that a lost device costs
          retries inside the cycle, not the cycle;
        * **mesh-rung-not-refused / mesh-refused-rung-served** — with
          the refusal leg configured, the clamped rung must appear in
          the refused census and must never be the rung a completed
          cycle ended on;
        * **mesh-not-healed** — the ladder must be back at rung 0 (and
          the refused census cleared) once the window closed and the
          drain ran."""
        out: list[Violation] = []
        sched = self.scheduler
        lad = sched.mesh_ladder if sched is not None else None
        if lad is None or not lad.enabled:
            out.append(Violation(
                "mesh-ladder-unarmed", tick,
                "device-loss fault configured but the scheduler has no "
                "ladder to walk (run with --mesh-devices >= 2)",
            ))
            return out
        if lad.max_rung_seen == 0:
            out.append(Violation(
                "mesh-ladder-never-engaged", tick,
                "the device-loss window never degraded the mesh — the "
                "injector did not reach the solve seam",
            ))
        w0 = self.faults.device_loss_at
        w1 = min(w0 + self.faults.device_loss_ticks, self.ticks)
        lost = [t for t in range(w0, w1) if t not in self._mesh_by_tick]
        if lost:
            out.append(Violation(
                "mesh-cycle-lost", lost[0],
                f"{len(lost)} tick(s) in the device-loss window never "
                f"completed a cycle: {lost[:8]} — the ladder must "
                "serve every cycle while degraded",
            ))
        refuse = int(self.faults.device_loss_refuse_devices)
        if refuse:
            samples = list(self._mesh_by_tick.values())
            if not any(refuse in s.get("refused", ()) for s in samples):
                out.append(Violation(
                    "mesh-rung-not-refused", tick,
                    f"the {refuse}-device rung was never HBM-refused "
                    "— the refusal leg did not fire",
                ))
            served_refused = [
                t for t, s in sorted(self._mesh_by_tick.items())
                if s.get("rung", 0) > 0 and s.get("devices") == refuse
                and refuse in s.get("refused", ())
            ]
            if served_refused:
                out.append(Violation(
                    "mesh-refused-rung-served", served_refused[0],
                    f"cycle(s) at {served_refused[:8]} ended on the "
                    f"HBM-refused {refuse}-device rung — a refused "
                    "rung must be skipped, never served",
                ))
        if lad.rung != 0:
            out.append(Violation(
                "mesh-not-healed", tick,
                f"ladder still at rung {lad.rung} ({lad.devices} "
                "device(s)) after the heal and the full drain — the "
                "canary streak never restored the mesh",
            ))
        return out

    def _open_tick_binds(self) -> int:
        """Bind requests received during FULLY-open breaker ticks
        (state "open" at the end of both the tick and its
        predecessor): the scheduler must have quiesced — zero."""
        total = 0
        for t, state in sorted(self._breaker_by_tick.items()):
            if state == "open" and \
                    self._breaker_by_tick.get(t - 1) == "open":
                total += self.cluster.bind_requests_by_tick.get(t, 0)
        return total

    def _open_tick_writes(self) -> int:
        """ALL write-verb requests (bind/evict/status; ping excluded —
        it is the heal probe) received during fully-open breaker
        ticks.  The pipelined commit must drain-then-quiesce on trip,
        so this is zero: no queued flush may leak onto the wire while
        the breaker is open."""
        total = 0
        for t, state in sorted(self._breaker_by_tick.items()):
            if state == "open" and \
                    self._breaker_by_tick.get(t - 1) == "open":
                total += self.cluster.write_requests_by_tick.get(t, 0)
        return total

    def _check_commit(self, tick: int) -> list[Violation]:
        """Pipelined-dimension assertions: per-pod wire-write order
        preserved (pipeline self-check; the wire-log replay's
        commit-order invariant covers the observable side), no op
        escaped its failure funnel, and the queue is fully drained —
        including through every breaker trip."""
        out: list[Violation] = []
        stats = self.commit.stats()
        if stats["order_violations"]:
            out.append(Violation(
                "commit-order", tick,
                f"{stats['order_violations']} op(s) of one ordering "
                "key observed running concurrently — per-pod "
                "wire-write order broken",
            ))
        if stats["flush_errors"]:
            out.append(Violation(
                "commit-flush-error", tick,
                f"{stats['flush_errors']} flush op(s) raised past the "
                "cache's failure funnels",
            ))
        if stats["depth"]:
            out.append(Violation(
                "commit-not-drained", tick,
                f"{stats['depth']} commit op(s) still in flight after "
                "the final drain barrier",
            ))
        writes_open = self._open_tick_writes()
        if writes_open:
            out.append(Violation(
                "write-while-open", tick,
                f"{writes_open} write request(s) reached the wire "
                "during fully-open breaker ticks — the commit "
                "pipeline did not drain-then-quiesce on trip",
            ))
        return out

    def _commit_summary(self) -> dict | None:
        base = {"mode": self.wire_commit}
        if self.commit is None:
            return base
        base.update(self.commit.stats())
        base["writes_while_open"] = self._open_tick_writes()
        return base

    # -- failover invariants -------------------------------------------
    def _check_failover(self, tick: int) -> list[Violation]:
        """Post-run assertions for the leader-crash scenario: the
        zombie window was actually exercised (≥1 stale-epoch write
        ATTEMPTED AND REJECTED), no stale write was accepted, the
        successor's epoch is strictly higher, and the takeover
        reconciliation classified the forged wreckage exactly.  The
        no-double-bind-across-leaders invariant needs no extra check:
        the wire-log replay spans both leaderships, so an accepted
        zombie bind already fails the per-tick double-bind check."""
        out: list[Violation] = []
        if self.fault_counts.get("leader-crash", 0) < 1:
            out.append(Violation(
                "leader-crash-not-fired", tick,
                "leader_crash_at configured but the crash never fired",
            ))
            return out
        if self.cluster.stale_epoch_rejections < 1:
            out.append(Violation(
                "zombie-window-not-exercised", tick,
                "leader-crash ran but no stale-epoch write was "
                "attempted and rejected — the fencing path went "
                "untested",
            ))
        if self._zombie_accepted:
            out.append(Violation(
                "stale-epoch-write-accepted", tick,
                f"{self._zombie_accepted} zombie write(s) from the "
                "dead epoch were ACCEPTED — single-writer-per-epoch "
                "broken",
            ))
        if self._crash_epochs is not None and \
                self._crash_epochs[1] <= self._crash_epochs[0]:
            out.append(Violation(
                "epoch-not-monotonic", tick,
                f"successor epoch {self._crash_epochs[1]} is not "
                f"higher than the crashed epoch {self._crash_epochs[0]}",
            ))
        if self._reconcile_summary is None:
            out.append(Violation(
                "failover-not-reconciled", tick,
                "the successor never ran the takeover reconciliation",
            ))
        elif self._forged is not None and (
            self._reconcile_summary["adopted"] != self._forged["adopted"]
            or self._reconcile_summary["rolled_back"]
            != self._forged["rolled_back"]
        ):
            out.append(Violation(
                "failover-reconcile-mismatch", tick,
                f"reconcile classified {self._reconcile_summary} but "
                f"the forged wreckage was {self._forged} — a frozen "
                "BINDING pod was mis-adopted or mis-rolled-back",
            ))
        return out

    def _failover_summary(self) -> dict | None:
        if not self.faults.leader_crash_at:
            return None
        old, new = self._crash_epochs or (0, 0)
        return {
            "crashes": self.fault_counts.get("leader-crash", 0),
            "old_epoch": old,
            "new_epoch": new,
            "stale_rejections": self.cluster.stale_epoch_rejections,
            "zombie_attempted": self._zombie_attempted,
            "zombie_accepted": self._zombie_accepted,
            "reconcile": self._reconcile_summary,
            "epoch_holders": {
                str(k): v
                for k, v in sorted(self.cluster.epoch_holders.items())
            },
        }

    # -- node-health invariants ----------------------------------------
    def _health_recovered(self) -> bool:
        """Full capacity restored: no node still cordoned or stuck in
        probation (suspect-with-decaying-score is schedulable and
        counts as recovered)."""
        if self.health is None:
            return True
        states = self.health.sample()["states"]
        return not any(
            s in ("cordoned", "probation") for s in states.values()
        )

    def _check_health_tick(self, tick: int, decisions) -> list[Violation]:
        """Per-tick health invariants, checked against this tick's
        drained wire-log decisions and the ledger samples at both tick
        boundaries:

        * **no-placement-on-cordoned** — zero accepted binds on a node
          cordoned at the END of both this tick and the previous one
          (a mid-tick cordon can race binds already dispatched; a
          FULLY cordoned tick cannot — same windowing as the
          breaker-open invariant);
        * **probation-canary-bounded** — binds accepted on a probation
          node never exceed the canary slots remaining at the start of
          the tick;
        * **gang-atomic-drain** — after a tick with drain evictions
          for a gang, no member of that gang may remain placed on any
          cordoned node (drain never strands part of a gang on the
          quarantined hardware)."""
        out: list[Violation] = []
        prev = self._health_by_tick.get(tick - 1, {})
        now = self._health_by_tick.get(tick, {})
        prev_states = prev.get("states", {})
        now_states = now.get("states", {})
        binds_by_node = collections.Counter(
            e.get("node") for e in decisions if e["op"] == "bind"
        )
        for n in sorted(prev_states):
            if prev_states[n] == "cordoned" and \
                    now_states.get(n) == "cordoned":
                c = binds_by_node.get(n, 0)
                if c:
                    self._cordoned_placements += c
                    out.append(Violation(
                        "placement-on-cordoned", tick,
                        f"{c} bind(s) accepted on node {n} during a "
                        "fully cordoned tick — the quarantine mask "
                        "leaked",
                    ))
        for n, remaining in sorted(prev.get(
            "canary_remaining", {},
        ).items()):
            if now_states.get(n) != "probation":
                # The node left probation DURING this tick (promoted
                # to OK at on_cycle — the clamp lifted before the
                # pack — or re-cordoned by a failure): last tick's
                # remaining no longer bounds this tick's binds.  Same
                # both-boundaries windowing as the cordon check.
                continue
            c = binds_by_node.get(n, 0)
            if c > remaining:
                self._canary_overruns += c - remaining
                out.append(Violation(
                    "probation-canary-exceeded", tick,
                    f"{c} bind(s) accepted on probation node {n} with "
                    f"only {remaining} canary slot(s) remaining",
                ))
        drained_groups = sorted({
            e.get("group") for e in decisions
            if e["op"] == "evict"
            and e.get("reason") == "drain-cordoned" and e.get("group")
        })
        if drained_groups:
            cordoned_now = {
                n for n, s in now_states.items() if s == "cordoned"
            }
            with self.cluster._lock:
                for g in drained_groups:
                    stuck = sorted(
                        p.name for p in self.cluster.pods.values()
                        if p.group == g and p.node in cordoned_now
                        and p.status in (TaskStatus.BOUND,
                                         TaskStatus.RUNNING)
                    )
                    if stuck:
                        out.append(Violation(
                            "gang-partial-drain", tick,
                            f"gang {g} drained this tick but "
                            f"member(s) {stuck} remain placed on "
                            "cordoned node(s) — drain was not "
                            "gang-atomic",
                        ))
        return out

    def _check_flaky(self, tick: int) -> list[Violation]:
        """Post-run assertions for the flaky-node scenario: quarantine
        actually engaged, the (live) wire breaker never tripped on the
        node's ANSWERED refusals while healthy-node binds flowed, and
        the node re-admitted through probation before the drain ended
        (convergence-after-heal)."""
        out: list[Violation] = []
        if self.fault_counts.get("flaky-node", 0) < 1:
            out.append(Violation(
                "flaky-never-fired", tick,
                "flaky_at configured but the flaky window never opened",
            ))
            return out
        if self.health.cordons_total < 1:
            out.append(Violation(
                "quarantine-never-engaged", tick,
                "flaky node refused binds / flapped NotReady but the "
                "health ledger never cordoned it",
            ))
        breaker = self.guardrails.breaker if self.guardrails else None
        if breaker is not None and breaker.opened_count and \
                not self.faults.blackhole_at:
            # With a blackhole window ALSO configured (the restart
            # scenario), the breaker legitimately trips on the dead
            # wire; only a flaky-only run can assert it never opened.
            out.append(Violation(
                "flaky-tripped-breaker", tick,
                "the wire breaker tripped during the flaky window — "
                "node-level refusals (answered by the transport) "
                "leaked into the global failure streak",
            ))
        if not self._health_recovered():
            states = self.health.sample()["states"]
            out.append(Violation(
                "health-not-recovered", tick,
                f"scenario drained but node(s) remain quarantined: "
                f"{states} — probation never re-admitted the healed "
                "hardware",
            ))
        return out

    def _health_summary(self) -> dict | None:
        if self.health is None:
            return None
        s = self.health.sample()
        return {
            "cordons": s["cordons_total"],
            "probation_failures": s["probation_failures_total"],
            "final_states": s["states"],
            "flaky_bind_faults": self.cluster.flaky_bind_failures,
            "cordoned_placements": self._cordoned_placements,
            "canary_overruns": self._canary_overruns,
            "drain_evictions": sum(
                1 for e in self._decisions
                if e["op"] == "evict"
                and e.get("reason") == "drain-cordoned"
            ),
        }

    # -- crash-restart invariants --------------------------------------
    def _check_restart(self, tick: int) -> list[Violation]:
        """Post-run assertions for the crash-restart scenario — the
        operational memory actually SURVIVED each restart:

        * **state-adopted** — every restart adopted durable state
          (journal or peer mirror; a cold adoption means the journal
          machinery silently wrote nothing);
        * **quarantine-survives-restart** — every node cordoned at a
          crash is cordoned after the restore (the per-tick
          placement-on-cordoned check then enforces ZERO post-restart
          placements on it);
        * **refusal-pin-survives / refused-bucket-never-recompiled** —
          the post-restart probe answered from the restored pin, with
          no fresh refusal count and no compiled executable at the
          pinned shapes;
        * **breaker-reopen-without-re-streak** — a breaker OPEN at the
          crash is OPEN after the restore, with zero write requests
          reaching the wire in between (the restored streak, not a
          fresh one, re-opened it)."""
        out: list[Violation] = []
        if self.fault_counts.get("crash-restart", 0) < 1:
            out.append(Violation(
                "crash-restart-not-fired", tick,
                "crash_restart_at configured but no restart fired",
            ))
            return out
        for r in self._restarts:
            if r["source"] is None:
                out.append(Violation(
                    "state-not-adopted", r["tick"],
                    "restart adopted no durable state — journal and "
                    "peer mirror both came back empty",
                ))
            lost = [
                n for n in r["pre_cordoned"]
                if r["post_states"].get(n) != "cordoned"
            ]
            if lost:
                out.append(Violation(
                    "quarantine-lost-across-restart", r["tick"],
                    f"node(s) {lost} were cordoned at the crash but "
                    "not after the restore — the restarted scheduler "
                    "re-trusts known-bad hardware",
                ))
            pins_lost = [
                s for s in r["pins_pre"] if s not in r["pins_post"]
            ]
            if pins_lost:
                out.append(Violation(
                    "refusal-pin-lost-across-restart", r["tick"],
                    f"HBM refusal pin(s) {pins_lost} did not survive "
                    "the restart",
                ))
            if r["breaker_pre"] == "open":
                if r["breaker_post"] != "open":
                    out.append(Violation(
                        "breaker-not-reopened", r["tick"],
                        "breaker was OPEN at the crash but not after "
                        "the restore — the restarted daemon would "
                        "re-fan-out into the dead wire",
                    ))
                if r["wire_writes_during_restart"]:
                    out.append(Violation(
                        "breaker-reopen-re-streak", r["tick"],
                        f"{r['wire_writes_during_restart']} write "
                        "request(s) reached the wire between the "
                        "crash and the breaker re-opening — the "
                        "restored breaker must open WITHOUT a fresh "
                        "failure streak",
                    ))
        if self.faults.hbm_pin_at:
            if self.fault_counts.get("hbm-pin", 0) < 1:
                out.append(Violation(
                    "hbm-pin-not-established", tick,
                    "hbm_pin_at configured but no persistent refusal "
                    "pin was established (no projection gap on this "
                    "backend?)",
                ))
            elif self._pin_probe is None:
                out.append(Violation(
                    "hbm-pin-probe-not-fired", tick,
                    "the post-restart pin probe never ran",
                ))
            else:
                p = self._pin_probe
                if not p["pinned"] or (
                    not p["shape_drifted"] and p["verdict"] is not False
                ):
                    out.append(Violation(
                        "refusal-pin-lost-across-restart", p["tick"],
                        f"post-restart probe found no valid pin: {p}",
                    ))
                if p["compiled_refused_shape"] or (
                    not p["shape_drifted"] and p["recompiled_refusals"]
                ):
                    out.append(Violation(
                        "refused-bucket-recompiled", p["tick"],
                        "the refused bucket was RECOMPILED after the "
                        f"restart instead of answering from the pin: "
                        f"{p}",
                    ))
        return out

    def _restart_summary(self) -> dict | None:
        if not self.faults.restart_faults:
            return None
        store = self.statestore
        return {
            "restarts": self.fault_counts.get("crash-restart", 0),
            "sequence": list(self._restarts),
            "pin_probe": self._pin_probe,
            "cordoned_placements": self._cordoned_placements,
            "mirrored": self.cluster.state_snapshot is not None,
            "journal": None if store is None else {
                "appends": store.appends,
                "compactions": store.compactions,
                "corrupt_dropped": store.corrupt_dropped,
                "cycle": store.cycle,
            },
        }

    # -- compile-artifact-bank invariants -------------------------------
    def _check_compile(self, tick: int) -> list[Violation]:
        """Post-run assertions for the compile-cliff scenario
        (doc/design/compile-artifacts.md) — the initialization cost
        actually became horizontal background work:

        * **compile-growth-not-exercised** — the run banked ≥ 2
          distinct programs (the base bucket plus a crossed growth
          bucket); anything less and the adoption checks are vacuous;
        * **artifact-not-mirrored** — the cluster-side mirror holds
          ≥ 1 entry (putCompileArtifact landed through the live wire);
        * **post-restart-inline-compile** — the successor incarnation
          compiled NOTHING inline: every program it served came from
          the bank (or the peer mirror in wipe mode);
        * **artifact-not-adopted** — the successor adopted ≥ 1 banked
          executable (and in peer mode, merged ≥ 1 entry from the
          wire mirror);
        * **cycle-blocked-on-compile** — no post-restart cycle spent
          more than COMPILE_BLOCK_BUDGET_S wall seconds inside
          compilation."""
        out: list[Violation] = []
        self._harvest_compile(self.scheduler, final=True)
        if self.faults.restart_faults and \
                self.fault_counts.get("crash-restart", 0) < 1:
            return out  # _check_restart already reports the no-fire
        banked = self._compile_totals.get("banked", 0)
        if banked < 2:
            out.append(Violation(
                "compile-growth-not-exercised", tick,
                f"only {banked} program(s) banked — the scenario "
                "never crossed a padding bucket, so the adoption "
                "invariants prove nothing",
            ))
        with self.cluster._lock:
            mirrored = len(self.cluster.compile_artifacts)
        if mirrored < 1:
            out.append(Violation(
                "artifact-not-mirrored", tick,
                "no compile artifact reached the cluster-side mirror "
                "(putCompileArtifact never landed)",
            ))
        final = self._compile_final or {}
        if self._restarts:
            if final.get("inline", 0):
                out.append(Violation(
                    "post-restart-inline-compile", tick,
                    f"the successor compiled {final['inline']} "
                    "program(s) INLINE instead of adopting its "
                    f"predecessor's artifacts: {final}",
                ))
            if not final.get("adopted", 0):
                out.append(Violation(
                    "artifact-not-adopted", tick,
                    f"the successor adopted no banked executable: "
                    f"{final}",
                ))
            if self.compile_bank_mode == 2 and not any(
                r.get("artifacts_peer_adopted", 0)
                for r in self._restarts
            ):
                out.append(Violation(
                    "artifact-not-adopted", tick,
                    "peer mode: no entry was merged from the wire "
                    "mirror at any restart (the local bank was wiped "
                    "— adoption MUST have come through "
                    "getCompileArtifact)",
                ))
            restart_tick = self._restarts[0]["tick"]
            worst = max(
                ((t, w) for t, w in self._compile_wait_by_tick.items()
                 if t > restart_tick),
                key=lambda p: p[1], default=(None, 0.0),
            )
            if worst[1] > COMPILE_BLOCK_BUDGET_S:
                out.append(Violation(
                    "cycle-blocked-on-compile", worst[0],
                    f"post-restart cycle spent {worst[1]:.2f}s blocked "
                    f"on compilation (> {COMPILE_BLOCK_BUDGET_S:.1f}s) "
                    "— the successor paid the compile cliff live",
                ))
        return out

    def _compile_summary(self) -> dict | None:
        if not self.compile_bank_mode:
            return None
        if self._compile_final is None and self.scheduler is not None:
            self._harvest_compile(self.scheduler, final=True)
        mirrored = 0
        if self.cluster is not None:
            with self.cluster._lock:
                mirrored = len(self.cluster.compile_artifacts)
        restart_tick = (
            self._restarts[0]["tick"] if self._restarts else None
        )
        post = {
            t: round(w, 4)
            for t, w in sorted(self._compile_wait_by_tick.items())
            if restart_tick is not None and t > restart_tick and w > 0
        }
        return {
            "mode": self.compile_bank_mode,
            "totals": dict(self._compile_totals),
            "post_restart": self._compile_final,
            "peer_adopted": sum(
                r.get("artifacts_peer_adopted", 0)
                for r in self._restarts
            ),
            "mirrored_entries": mirrored,
            "bank_entries": getattr(self, "_bank_entries_final", 0),
            "max_post_restart_compile_wait_s": round(
                max(post.values(), default=0.0), 4
            ),
            "post_restart_compile_waits": post,
        }

    # -- batched-ingest invariants --------------------------------------
    def _harvest_ingest(self, adapter) -> None:
        """Fold one (dying) adapter incarnation's ingest counters into
        the run totals."""
        s = self._ingest_stats
        s["events"] += getattr(adapter, "events_seen", 0)
        s["batches"] += getattr(adapter, "batches_applied", 0)
        s["coalesced"] += getattr(adapter, "coalesced_events", 0)

    def _mirror_divergence(self) -> list[str]:
        """(uid, field) mismatches between the scheduler's mirror and
        the authoritative cluster — the serially-applied oracle the
        no-event-lost / latest-wins invariants compare against.  Empty
        when every pod the cluster holds is mirrored with the same
        (status, node) and nothing extra lingers.  Memoized: the
        post-run world is static, and both the check and the summary
        read it."""
        if getattr(self, "_mirror_div_memo", None) is not None:
            return self._mirror_div_memo
        with self.cluster._lock:
            truth = {
                uid: (p.status.name, p.node)
                for uid, p in self.cluster.pods.items()
            }
        with self.cache.lock():
            mirror = {
                uid: (p.status.name, p.node)
                for uid, p in self.cache._pods.items()
            }
        out = []
        for uid in sorted(set(truth) | set(mirror)):
            t, m = truth.get(uid), mirror.get(uid)
            if t != m:
                out.append(f"{uid}: cluster={t} mirror={m}")
        self._mirror_div_memo = out
        return out

    def _check_ingest(self, tick: int) -> list[Violation]:
        """Post-run assertions for the event-storm scenario: the storm
        actually fired, no event was lost and latest-wins coalescing
        preserved semantics (the quiesced end state mirrors the
        cluster exactly — the cluster IS the serially-applied oracle),
        and the storm + mid-storm relist never SUSTAINEDLY starved the
        cycle thread: reaching OVERLOADED is only a violation when the
        ladder is still engaged after the drain.  (The rungs are
        WALL-clocked — a cold compile or a loaded CI host can spike
        one transiently, the PR-8 lesson — while real ingest
        starvation keeps overrunning and never walks back down.  The
        hard liveness backstops are the per-tick quiesce timeout and
        the convergence deadline, which a wedged ingest thread fails
        outright.)"""
        out: list[Violation] = []
        if self.fault_counts.get("event-storm", 0) < 1:
            out.append(Violation(
                "storm-never-fired", tick,
                "storm_at configured but no event-storm burst fired",
            ))
            return out
        diverged = self._mirror_divergence()
        if diverged:
            out.append(Violation(
                "ingest-mirror-divergence", tick,
                f"{len(diverged)} pod(s) diverged from the cluster "
                f"after the storm (events lost or mis-coalesced): "
                f"{'; '.join(diverged[:5])}",
            ))
        if self.guardrails is not None and \
                self.guardrails.max_rung_seen >= 2 and \
                self.guardrails.rung > 0:
            out.append(Violation(
                "ingest-starved-cycle", tick,
                "the cycle watchdog reached OVERLOADED during the "
                "event-storm run and was STILL degraded after the "
                "drain — ingest lock traffic starved the cycle thread",
            ))
        return out

    def _ingest_summary(self) -> dict | None:
        base = {"mode": self.ingest_mode}
        base.update(self._ingest_stats)
        if self.faults.ingest_faults:
            base["storm_bursts"] = self.fault_counts.get(
                "event-storm", 0,
            )
            base["mirror_divergence"] = len(self._mirror_divergence())
            if self.guardrails is not None:
                base["max_rung_seen"] = self.guardrails.max_rung_seen
                base["final_rung"] = self.guardrails.rung
        return base

    def _check_guardrails(self, tick: int) -> list[Violation]:
        """Post-run assertions that the self-protection layer actually
        engaged, quiesced, and recovered — violations ride the same
        flight-recorder/exit-code path as scheduling invariants."""
        out: list[Violation] = []
        rails = self.guardrails
        breaker = rails.breaker if rails is not None else None
        if self.faults.slow_at and rails.max_rung_seen < 1:
            out.append(Violation(
                "ladder-never-engaged", tick,
                "slow-backend window ran but the cycle watchdog never "
                "left rung 0 (no degradation under sustained overrun)",
            ))
        if self.faults.blackhole_at:
            if breaker is None or breaker.opened_count < 1:
                out.append(Violation(
                    "breaker-never-tripped", tick,
                    "bind-blackhole window ran but the wire breaker "
                    "never tripped open",
                ))
            elif breaker.closed_count < 1:
                out.append(Violation(
                    "breaker-never-closed", tick,
                    "wire breaker tripped but never recovered after "
                    "the blackhole healed (half-open probe broken?)",
                ))
            binds_open = self._open_tick_binds()
            if binds_open:
                out.append(Violation(
                    "bind-while-open", tick,
                    f"{binds_open} bind request(s) reached the wire "
                    "during fully-open breaker ticks — scheduling did "
                    "not quiesce",
                ))
        if (
            self.faults.blackhole_at
            and self.trace_obs == "on"
            and breaker is not None
            and breaker.opened_count >= 1
        ):
            out.extend(self._check_flight_dump(tick))
        if self.faults.hbm_pressure_at and \
                self.fault_counts.get("hbm-pressure", 0) < 1:
            out.append(Violation(
                "hbm-admission-not-exercised", tick,
                "hbm-pressure fault fired but no refusal was recorded "
                "(warm_grown skipped or admitted over a 1-byte "
                "ceiling)",
            ))
        if not self._rails_recovered():
            out.append(Violation(
                "guardrail-not-recovered", tick,
                f"scenario drained but the daemon is still degraded "
                f"(rung {rails.rung} {rails.state!r}, breaker "
                f"{rails.breaker_state()!r})",
            ))
        return out

    def _check_flight_dump(self, tick: int) -> list[Violation]:
        """The always-on flight recorder must have auto-dumped a
        post-mortem ON the tick the breaker tripped, and the dump must
        name the triggering transition — the production promise the
        chaos run exists to prove (doc/design/observability.md)."""
        out: list[Violation] = []
        # The live tracer (the checks run before teardown harvests
        # it); the summary is the fallback for post-teardown callers.
        tracer = trace_obs_mod.get()
        all_dumps = (
            list(tracer.recorder.dumps) if tracer is not None
            else (self._trace_summary or {}).get("dumps", ())
        )
        dumps = [
            d for d in all_dumps if d.get("trigger") == "breaker-open"
        ]
        if not dumps:
            out.append(Violation(
                "flight-dump-missed-trip", tick,
                "the wire breaker tripped open but the always-on "
                "flight recorder never auto-dumped a 'breaker-open' "
                "post-mortem",
            ))
            return out
        # The trip tick: first end-of-tick sample where the breaker
        # reads open after a non-open tick (the sample convention the
        # breaker-open invariant already uses).
        trip = None
        prev = "closed"
        for t in sorted(self._breaker_by_tick):
            state = self._breaker_by_tick[t]
            if state == "open" and prev != "open":
                trip = t
                break
            prev = state
        if trip is None:
            return out  # opened and re-closed within one tick: no
            #             stable trip tick to pin the dump against
        before = self._trace_dumps_by_tick.get(trip - 1, 0)
        at = self._trace_dumps_by_tick.get(trip)
        if at is not None and at <= before:
            out.append(Violation(
                "flight-dump-missed-trip", tick,
                f"breaker tripped at tick {trip} but the flight "
                f"recorder's auto-dump count did not advance that tick "
                f"({before} -> {at})",
            ))
        return out

    def _guardrail_summary(self) -> dict | None:
        rails = self.guardrails
        if rails is None:
            return None
        breaker = rails.breaker
        return {
            "max_rung_seen": rails.max_rung_seen,
            "final_state": rails.state,
            "final_breaker": rails.breaker_state(),
            "breaker_opened": breaker.opened_count if breaker else 0,
            "breaker_closed": breaker.closed_count if breaker else 0,
            "blackholed_requests": self.cluster.blackholed_requests,
            "binds_while_open": self._open_tick_binds(),
            "hbm_refusals": rails.hbm.refusals,
        }

    # -- helpers --------------------------------------------------------
    def _all_settled(self) -> bool:
        with self.cluster._lock:
            return all(
                p.status in (TaskStatus.BOUND, TaskStatus.RUNNING)
                for p in self.cluster.pods.values()
            )

    def _final_assignment(self) -> dict[str, str]:
        with self.cluster._lock:
            return {
                uid: p.node
                for uid, p in sorted(self.cluster.pods.items())
                if p.node is not None
            }

    def _teardown(self) -> None:
        tracer = trace_obs_mod.get()
        if self.trace_obs == "on" and tracer is not None:
            # Harvest BEFORE disabling: the summary (incl. which
            # triggers auto-dumped, and when) survives into the
            # ChaosResult after the dump files themselves are removed
            # with the engine-owned temp dir below.
            self._trace_summary = {
                "enabled": True,
                "dumps": [dict(d) for d in tracer.recorder.dumps],
                "spans_recorded":
                    tracer.spans.stats()["spans_recorded"],
                "decision_records":
                    tracer.decisions.stats()["records_total"],
                "transitions": len(tracer.recorder.transitions),
            }
        elif self._trace_summary is None:
            self._trace_summary = {"enabled": False}
        trace_obs_mod.disable()
        if self._trace_dump_dir is not None:
            import shutil

            shutil.rmtree(self._trace_dump_dir, ignore_errors=True)
        if self.adapter is not None:
            self._harvest_ingest(self.adapter)
        if self.compile_bank is not None:
            # Entry census BEFORE the owned state dir (and the bank
            # under it) is removed below.
            self._bank_entries_final = len(self.compile_bank.entries())
        if self.statestore is not None:
            try:
                # Final compaction + mirror (the wire may already be
                # down — the sink swallows).
                self.statestore.close()
            except Exception:  # noqa: BLE001 — best effort on the way down
                pass
        if self._state_dir_owned and self.state_dir is not None:
            # The engine mkdtemp'd this journal dir; repeated chaos/CI
            # runs must not accumulate stale state dirs in /tmp.
            import shutil

            shutil.rmtree(self.state_dir, ignore_errors=True)
        if self.commit is not None:
            try:
                self.commit.close(timeout=COMMIT_DRAIN_TIMEOUT)
            except Exception:  # noqa: BLE001 — best effort on the way down
                pass
        try:
            if self._have_lease and self.backend is not None:
                self.backend.release_lease(self._holder)
        except Exception:  # noqa: BLE001 — best effort on the way down
            pass
        for sock in self._socks:
            try:
                sock.close()
            except OSError:
                pass
