"""`python -m kube_batch_tpu.chaos` — the chaos scenario CLI.

Exit codes: 0 = scenario completed with zero invariant violations and
converged; 1 = an invariant failed (the flight-recorder dump path is
printed); 2 = the harness itself broke (dead wire, quiesce timeout).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import sys

from kube_batch_tpu.chaos.engine import ChaosEngine, ChaosEngineError
from kube_batch_tpu.chaos.faults import FaultSpec
from kube_batch_tpu.chaos.workload import ScenarioSpec, read_trace


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m kube_batch_tpu.chaos",
        description="Deterministic fault-injecting cluster simulation "
                    "driving the real scheduler over its wire protocol, "
                    "with per-tick invariant checking and a flight "
                    "recorder.",
    )
    p.add_argument("--seed", type=int, default=None,
                   help="scenario seed: same seed ⇒ identical trace "
                        "hash and final assignment (default: the seed "
                        "recorded in a replayed trace's meta header, "
                        "else 0)")
    p.add_argument("--ticks", type=int, default=200,
                   help="scenario horizon in discrete ticks")
    p.add_argument("--scenario", default=None,
                   help="a recorded .jsonl trace to replay, or a JSON "
                        "file of {workload: {...}, faults: {...}} "
                        "spec overrides (default: built-in spec)")
    p.add_argument("--scheduler-conf", default=None,
                   help="policy YAML for the driven scheduler "
                        "(default: the built-in default policy)")
    p.add_argument("--no-faults", action="store_true",
                   help="run the workload churn with fault injection "
                        "disabled (baseline determinism runs); on a "
                        "replayed trace this also strips the recorded "
                        "inline fault events")
    p.add_argument("--drain", type=int, default=80,
                   help="post-scenario ticks every admissible gang "
                        "must converge within")
    p.add_argument("--record", type=int, default=64,
                   help="flight-recorder depth: last K ticks kept for "
                        "the post-mortem dump")
    p.add_argument("--trace-out", default=None,
                   help="write the scenario's replayable JSONL trace "
                        "(workload + fault plan) to this path")
    p.add_argument("--dump-dir", default=None,
                   help="directory for flight-recorder dumps "
                        "(default: the system temp dir)")
    p.add_argument("--corrupt-tick", type=int, default=None,
                   help="deliberately force a double-bind at this tick "
                        "(invariant-checker self-test: the run MUST "
                        "fail and dump)")
    p.add_argument("--wire-commit", choices=("sync", "pipelined"),
                   default=None,
                   help="commit dimension: 'pipelined' flushes binds/"
                        "status writes through the asynchronous commit "
                        "pipeline (per-pod ordering, drain barrier per "
                        "tick, extra invariants: wire-write order, "
                        "zero in-flight writes while the breaker is "
                        "open, drained queue); default: the mode "
                        "recorded in a replayed trace's meta header, "
                        "else 'sync'")
    p.add_argument("--pack-mode", choices=("incremental", "full"),
                   default=None,
                   help="tensor-pack strategy for the scheduler under "
                        "test (default: adopt from a replayed trace's "
                        "meta header, else incremental).  Pack mode is "
                        "decision-invisible: the same seed must hash "
                        "identically under both (make chaos pins it)")
    p.add_argument("--ingest-mode", choices=("batched", "event"),
                   default=None,
                   help="watch-ingest dimension for the driven "
                        "adapter: 'batched' (coalesced one-lock "
                        "batches + diff relist) or 'event' (the "
                        "per-event differential baseline).  Ingest "
                        "mode is decision-invisible: the same seed "
                        "must hash identically under both (make chaos "
                        "pins it).  Default: adopt from a replayed "
                        "trace's meta header, else 'batched'")
    p.add_argument("--trace", choices=("on", "off"), default="on",
                   dest="trace_obs",
                   help="always-on observability dimension "
                        "(kube_batch_tpu/trace/): 'on' (default — the "
                        "production posture) runs the scenario with "
                        "span tracing, decision records and the "
                        "anomaly-triggered flight recorder live, and "
                        "breaker-tripping scenarios ASSERT the "
                        "auto-dump fired on the trip tick; 'off' is "
                        "the parity baseline — tracing is decision-"
                        "invisible, so the same seed must hash "
                        "identically either way (pinned by "
                        "tests/test_chaos_trace.py)")
    p.add_argument("--autopilot", choices=("on", "off"), default=None,
                   help="fleet-autopilot dimension for cells mode "
                        "(doc/design/fleet-autopilot.md): 'on' runs a "
                        "per-cell rebalancer on each leader that turns "
                        "sustained SLO burn + pending demand into "
                        "epoch-fenced capacity claims automatically; "
                        "'off' forces it off even when the scenario's "
                        "cells section sets autopilot — the parity "
                        "baseline (the same seed must hash identically "
                        "to a run of the scenario without autopilot).  "
                        "Default: follow the scenario's cells.autopilot")
    p.add_argument("--mesh-devices", type=int, default=None,
                   help="device-mesh dimension for the scheduler under "
                        "test (doc/design/multichip-shard.md): N>1 "
                        "arms a virtual host-device mesh and runs the "
                        "node-axis sharded pack/solve.  The mesh is "
                        "decision-invisible: the same seed must hash "
                        "identically at any device count (make chaos "
                        "pins 1 vs 8).  Default: adopt from a replayed "
                        "trace's meta header, else 1")
    p.add_argument("--joint-solve", choices=("on", "off"), default=None,
                   help="cycle-solver dimension (doc/design/"
                        "joint-solve.md): 'on' runs the scheduler "
                        "under test with the joint single-solve cycle "
                        "(KB_TPU_JOINT_SOLVE=1), 'off' forces the "
                        "sequential four-pass program.  The joint "
                        "solve is decision-invisible wherever the "
                        "sequential outcome is policy-complete, so "
                        "eviction-free seeds must hash identically "
                        "under both (make chaos pins it); where it "
                        "admits MORE (post-eviction sweep) the "
                        "divergence is the documented improvement.  "
                        "Default: inherit the environment")
    p.add_argument("--compile-bank", choices=("auto", "on", "off"),
                   default="auto",
                   help="AOT compile-artifact bank dimension "
                        "(doc/design/compile-artifacts.md): 'auto' "
                        "(default) follows the scenario's "
                        "faults.compile_bank; 'off' is the decision-"
                        "invisibility parity run — adopting a banked "
                        "executable and compiling it fresh are the "
                        "same program, so the same seed must hash "
                        "identically either way (make chaos pins it)")
    p.add_argument("--cells", type=int, default=0,
                   help="multi-cell mode (doc/design/multi-cell.md): "
                        "drive N REAL schedulers — one per cell, each "
                        "with its own cache/adapter/fenced backend — "
                        "against one cluster, with partition faults "
                        "(full / asymmetric / straddling-reclaim), "
                        "cross-cell zombie probes and the wire-"
                        "negotiated capacity reclaim.  0 (default) = "
                        "the classic single-scheduler engine; a "
                        "scenario JSON with a 'cells' section implies "
                        "this mode")
    p.add_argument("--quiet", action="store_true",
                   help="suppress progress logging; print only the "
                        "summary JSON")
    return p


def _load_scenario(path: str) -> tuple:
    """(events, workload_spec, fault_spec, cell_spec, cell_workloads)
    from --scenario."""
    if path.endswith(".jsonl"):
        return read_trace(path), None, None, None, None
    with open(path, "r", encoding="utf-8") as f:
        raw = json.load(f)
    unknown = set(raw) - {"workload", "faults", "cells", "cell_workloads"}
    if unknown:
        raise SystemExit(
            f"--scenario {path}: unknown sections {sorted(unknown)} "
            "(known: ['workload', 'faults', 'cells', 'cell_workloads'])"
        )

    def _build(cls, section):
        fields = {f.name for f in dataclasses.fields(cls)}
        bad = set(section) - fields
        if bad:
            raise SystemExit(
                f"--scenario {path}: unknown {cls.__name__} keys "
                f"{sorted(bad)} (known: {sorted(fields)})"
            )
        # JSON arrays decode as lists; the spec fields are tuples.
        coerced = {
            k: tuple(tuple(x) if isinstance(x, list) else x for x in v)
            if isinstance(v, list) else v
            for k, v in section.items()
        }
        return cls(**coerced)

    from kube_batch_tpu.chaos.cells import CellFaultSpec

    return (
        None,
        _build(ScenarioSpec, raw.get("workload", {})),
        _build(FaultSpec, raw.get("faults", {})),
        _build(CellFaultSpec, raw["cells"]) if "cells" in raw else None,
        raw.get("cell_workloads"),
    )


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=logging.WARNING if args.quiet else logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    from kube_batch_tpu.cli import honor_jax_platforms

    honor_jax_platforms()
    if args.joint_solve is not None:
        # The scheduler under test reads the env var at construction;
        # both engines (classic and cells) build their schedulers
        # after this point.
        import os

        os.environ["KB_TPU_JOINT_SOLVE"] = (
            "1" if args.joint_solve == "on" else "0"
        )
    events, scenario, faults = (None, None, None)
    cell_spec, cell_workloads = None, None
    if args.scenario:
        events, scenario, faults, cell_spec, cell_workloads = \
            _load_scenario(args.scenario)

    if args.cells or cell_spec is not None:
        # Multi-cell mode: N real schedulers against one cluster
        # (doc/design/multi-cell.md).  Its own engine — the classic
        # flags that make no sense here (--wire-commit, --corrupt-tick,
        # trace replay) are refused rather than silently ignored.
        import dataclasses as _dc

        from kube_batch_tpu.chaos.cells import (
            CellChaosEngine,
            CellFaultSpec,
        )

        if events is not None:
            raise SystemExit("--cells does not replay .jsonl traces")
        unsupported = [
            flag for flag, hit in (
                ("--wire-commit", args.wire_commit is not None),
                ("--corrupt-tick", args.corrupt_tick is not None),
                ("--trace-out", args.trace_out is not None),
                ("--pack-mode", args.pack_mode is not None),
                ("--compile-bank", args.compile_bank != "auto"),
                ("--no-faults", args.no_faults),
            ) if hit
        ]
        if unsupported:
            raise SystemExit(
                f"cells mode does not support {', '.join(unsupported)} "
                "(the cells engine runs sync commits with its own "
                "fault family; see doc/design/multi-cell.md)"
            )
        spec = cell_spec or CellFaultSpec()
        if args.cells:
            spec = _dc.replace(spec, cells=args.cells)
        from kube_batch_tpu.compile_cache import enable_compile_cache

        enable_compile_cache()
        engine = CellChaosEngine(
            seed=args.seed or 0,
            ticks=args.ticks,
            scenario=scenario,
            cell_workloads=cell_workloads,
            cell_faults=spec,
            conf_path=args.scheduler_conf,
            record=args.record,
            drain=args.drain,
            dump_dir=args.dump_dir,
            ingest_mode=args.ingest_mode,
            trace_obs=args.trace_obs,
            autopilot=args.autopilot,
        )
        try:
            result = engine.run()
        except ChaosEngineError as exc:
            logging.error("chaos-cells harness failed: %s", exc)
            return 2
        print(json.dumps(result.summary(), indent=1, sort_keys=True))
        return 0 if result.ok else 1

    if args.autopilot is not None:
        raise SystemExit("--autopilot only applies to cells mode "
                         "(--cells N or a scenario with a 'cells' "
                         "section)")
    if args.no_faults:
        faults = FaultSpec.none()
        if events is not None:
            # A replayed trace carries its fault schedule inline;
            # "no faults" must strip those too, not just zero the
            # bind-curse percentage.
            events = [e for e in events if e.get("op") != "fault"]
    # Resolved AFTER --no-faults: a fault-stripped replay of a
    # compile-bank scenario runs bank-less, so it should keep the
    # persistent cache too (the cache-replays-are-not-bankable rule
    # only matters when something is banking).
    bank_on = args.compile_bank == "on" or (
        args.compile_bank == "auto"
        and faults is not None and faults.compile_bank
    )
    if bank_on:
        # The artifact-bank scenario needs TRUE compiles: an
        # executable REPLAYED from the persistent XLA cache cannot be
        # re-serialized (XLA drops the AOT symbol table on the load
        # path), so a warm cache would leave the bank empty and the
        # adoption invariants vacuous.  The scenario's few tiny-shape
        # compiles cost seconds.
        logging.info("compile-bank scenario: persistent XLA compile "
                     "cache disabled for this run (cache replays are "
                     "not bankable)")
    else:
        from kube_batch_tpu.compile_cache import enable_compile_cache

        # Same persistent-cache policy as the daemon CLI: a rerun of
        # the same scenario shapes replays its fused-cycle compiles
        # from disk.
        enable_compile_cache()
    seed = args.seed
    if seed is None:
        meta = next(
            (e for e in events or () if e.get("op") == "meta"), None
        )
        seed = int(meta.get("seed", 0)) if meta else 0

    # The virtual device mesh must be armed BEFORE the first jax
    # backend touch (XLA reads the host-device count exactly once), so
    # the replayed-trace meta adoption the engine would do is resolved
    # here too — a mesh=8 trace replayed without the flag still runs
    # on 8 devices.
    from kube_batch_tpu.parallel.mesh import (
        arm_virtual_devices,
        resolve_mesh_devices,
    )

    mesh_devices = args.mesh_devices
    if mesh_devices is None and events is not None:
        meta = next(
            (e for e in events if e.get("op") == "meta"), None
        )
        if meta is not None and meta.get("mesh_devices") is not None:
            mesh_devices = int(meta["mesh_devices"])
    mesh_n = resolve_mesh_devices(mesh_devices)
    if mesh_n > 1:
        arm_virtual_devices(mesh_n)
        logging.info("chaos mesh: armed %d virtual host devices", mesh_n)

    engine = ChaosEngine(
        seed=seed,
        ticks=args.ticks,
        scenario=scenario,
        faults=faults,
        events=events,
        conf_path=args.scheduler_conf,
        record=args.record,
        drain=args.drain,
        trace_path=args.trace_out,
        dump_dir=args.dump_dir,
        corrupt_tick=args.corrupt_tick,
        wire_commit=args.wire_commit,
        pack_mode=args.pack_mode,
        ingest_mode=args.ingest_mode,
        trace_obs=args.trace_obs,
        compile_bank=args.compile_bank,
        mesh_devices=mesh_n,
    )
    try:
        result = engine.run()
    except ChaosEngineError as exc:
        logging.error("chaos harness failed: %s", exc)
        return 2
    print(json.dumps(result.summary(), indent=1, sort_keys=True))
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
