"""Multi-cell chaos: N REAL schedulers, one fleet, partitions injected.

The capstone scenario of doc/design/multi-cell.md: the fleet is
partitioned into cells (nodes/queues carry a cell assignment), each
cell runs a FULL scheduler stack — its own SchedulerCache, cell-scoped
WatchAdapter, cell-fenced StreamBackend, Guardrails and Scheduler —
against ONE ChaosCellCluster, generalizing PR 4's
restart-as-second-elector machinery into N live concurrent
incarnations in one process.  The engine drives them tick by tick in
strict cell order (which is what keeps a two-writer threaded wire
stack deterministic: same seed ⇒ same trace hash), and injects the
fault class a single-writer fleet can never see:

* **cross-cell zombie writes** — a cell-A scheduler attempts a bind
  onto a cell-B node, once through the raw wire (the CLUSTER's
  cell-scope fence must reject it with the structured ``CellScope``
  code) and once through the normal bind seam (the CLIENT's local
  cell fence must fail it without burning the RTT);
* **full partition** — one cell loses ALL verbs and all watch
  broadcasts: its scheduler stands down, the PEER cell must keep
  placing (partitioned-cell-peer-unaffected), and after heal the dark
  cell resumes its watch from the missed tail and re-converges with
  zero double-binds across the boundary;
* **asymmetric partition** — the half-open network case: the watch
  stays LIVE but every write is black-holed, so the cell's wire
  breaker must trip against a peer it can still see, quiesce, and
  heal through the half-open probe once the partition lifts;
* **partition-straddling reclaim** — a starved cell's capacity claim
  is pending when its donor goes dark: the claim must time out and
  roll back (reclaim-atomic-or-rolled-back — no node ever leaks into
  limbo), and the re-claim after heal must land.

Cross-cell reclaim itself runs through the wire protocol's
negotiation verbs (claimCapacity / offerCapacity / listClaims,
client/external.py): the starved cell claims, the donor cell's OWN
scheduler gang-atomically evicts the fullest-empty node's residents
through its normal evict seam and offers the freed node, and the
cluster re-cells it atomically — no writer ever touches another
cell's state.

Engine invariants (on top of the classic per-tick checker, whose
epoch replay is per-cell here): no-cross-cell-write-accepted,
single-writer-per-cell-epoch, reclaim-atomic-or-rolled-back,
partitioned-cell-peer-unaffected, and convergence-after-heal across
both cells.  `make chaos` runs `examples/chaos-cells.json` twice plus
an --ingest-mode event parity run through
scripts/check_chaos_cells.py.
"""

from __future__ import annotations

import collections
import dataclasses
import json
import logging
import os
import socket
import tempfile
import time
import types

from kube_batch_tpu import metrics, scope
from kube_batch_tpu import trace as trace_obs_mod
from kube_batch_tpu.api.resource import ResourceSpec
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.cache.cache import SchedulerCache
from kube_batch_tpu.chaos.engine import (
    GUARDRAIL_ENGAGE_AFTER,
    GUARDRAIL_RECOVER_AFTER,
    GUARDRAIL_RESET_TICKS,
    GUARDRAIL_TRIP_AFTER,
    GUARDRAIL_WATCHDOG_PERIOD,
    ChaosEngineError,
    FlightRecorder,
)
from kube_batch_tpu.chaos.faults import ChaosCluster
from kube_batch_tpu.chaos.invariants import InvariantChecker, Violation
from kube_batch_tpu.chaos.workload import (
    ScenarioSpec,
    apply_to_cluster,
    generate,
    trace_hash,
)
from kube_batch_tpu.client.adapter import (
    CELL_LABEL,
    CellScopeError,
    StreamBackend,
    WatchAdapter,
    resume_session,
)
from kube_batch_tpu.scheduler import Scheduler

log = logging.getLogger(__name__)

GI = float(1 << 30)
LEASE_TTL = 1e9  # ticks are the only clock; partitions break renewals
#: Wire round-trip timeout while an asymmetric partition is
#: configured: a black-holed bind must fail in seconds (same rationale
#: as the classic engine's BLACKHOLE_WIRE_TIMEOUT).
ASYM_WIRE_TIMEOUT = 1.5

# -- SLO engine wiring (trace/slo.py), tick-clocked ------------------------
#: The cells run arms each cell's tracer with a TICK-clocked SLO
#: engine.  The deterministic invariant rides the CYCLE objective: a
#: cycle that ran feeds its real (tiny) wall latency through
#: Tracer.end_cycle — always under the generous threshold, so a
#: healthy cell never reads bad on wall-clock noise (the PR-8
#: lesson) — while every stood-down tick (full partition, lease
#: unreachable) feeds one synthetic bad observation.  The dark window
#: therefore drives a fast burn EXACTLY over its ticks, and the
#: healed cell's sliding windows clear it.
CYCLE_SLO_THRESHOLD_S = 30.0
CYCLE_SLO_BAD_VALUE = 2 * CYCLE_SLO_THRESHOLD_S
#: Placement objective (informational, rides the summary): pending
#: pods older than this many ticks burn; first placements observe
#: their age.
PLACEMENT_SLO_THRESHOLD_TICKS = 3.0
#: Multi-window pairs in TICKS: (short, long, burn threshold).
SLO_FAST = (3.0, 6.0, 4.0)
SLO_SLOW = (6.0, 12.0, 2.0)
#: Ticks past a partition heal within which the victim's fast burn
#: must still have been observed flagged (evaluation trails the
#: window by up to a bucket).
SLO_FLAG_GRACE_TICKS = 2


@dataclasses.dataclass(frozen=True)
class CellFaultSpec:
    """The cells scenario's fault schedule + reclaim/starvation knobs
    (examples/chaos-cells.json · "cells" section)."""

    #: Number of cells (each gets a full scheduler stack).
    cells: int = 2
    #: Full partition window: the victim cell loses every verb AND all
    #: watch broadcasts.  0 disables.
    full_partition_at: int = 0
    full_partition_ticks: int = 4
    full_partition_cell: int = 1   # index into sorted cell names
    #: Asymmetric partition window: watch live, writes black-holed —
    #: the victim's breaker must trip with a live peer.  0 disables.
    asym_partition_at: int = 0
    asym_partition_ticks: int = 3
    asym_partition_cell: int = 1
    #: Cross-cell zombie-write probes: at `xcell_probe_at` and every
    #: `xcell_probe_every` ticks after, a live cell attempts a bind
    #: onto a foreign node (cluster fence) and through its bind seam
    #: (local fence).  Every one must be rejected.  0 disables.
    xcell_probe_at: int = 2
    xcell_probe_every: int = 8
    #: Deterministic starvation: at `starve_at` one all-or-nothing
    #: gang lands in `starve_cell` sized past that cell's whole
    #: capacity, forcing the reclaim negotiation.  0 pods disables.
    starve_at: int = 0
    starve_pods: int = 0
    starve_cell: int = 0
    starve_cpu_milli: float = 4000.0
    starve_mem_gi: float = 2.0
    #: Structural-starvation trigger: a cell claims once its pending
    #: demand has exceeded its TOTAL capacity for this many ticks.
    reclaim_after_ticks: int = 2
    #: Claim TTL in ticks: a donor that never answers (partition!)
    #: rolls the claim back at created + ttl.
    reclaim_ttl_ticks: int = 3
    #: Straddle window: a FULL partition of the DONOR cell timed to
    #: strand a pending claim — it must roll back, then the re-claim
    #: after heal must land.  0 disables.
    straddle_at: int = 0
    straddle_ticks: int = 4
    #: Fleet autopilot (kube_batch_tpu/autopilot/): when true the
    #: engine replaces the manual claim/donor duties with a per-cell
    #: Autopilot — demand signal + SLO burn join + hysteresis ladder
    #: driving multi-node claims.  The --autopilot CLI flag overrides
    #: either way; OFF leaves every decision byte-identical to the
    #: manual path (the knobs below are then inert).
    autopilot: bool = False
    autopilot_arm_after: int = 2
    autopilot_quiet_after: int = 2
    autopilot_cooldown_ticks: int = 3
    autopilot_max_nodes: int = 2
    autopilot_headroom_cpu_milli: float = 0.0
    autopilot_burn_memory: int = 3

    @property
    def donor_cell_default(self) -> int:
        """The straddle partitions the donor of `starve_cell`'s
        claims: the first OTHER cell in sorted order."""
        return 1 if self.starve_cell == 0 else 0


def cellify(events: list[dict], cell: str) -> list[dict]:
    """Stamp one cell's identity onto a generated event schedule:
    queues/nodes get cell-prefixed names plus the cell assignment
    (queues as a first-class field, nodes via the `cell` label);
    submits follow their renamed queue.  Gang/pod identities are
    already unique per cell (the generator keys them on the derived
    per-cell seed)."""
    out = []
    for e in events:
        e = json.loads(json.dumps(e))  # deep, shared-nothing copy
        op = e["op"]
        if op == "add-queue":
            e["name"] = f"{cell}-{e['name']}"
            e["cell"] = cell
        elif op == "add-node":
            node = e["node"]
            node["name"] = f"{cell}-{node['name']}"
            node["uid"] = f"uid-node-{node['name']}"
            node.setdefault("labels", {})[CELL_LABEL] = cell
        elif op == "remove-node":
            e["name"] = f"{cell}-{e['name']}"
        elif op == "submit":
            e["queue"] = f"{cell}-{e.get('queue', 'default')}"
        out.append(e)
    return out


def plan_cell_faults(spec: CellFaultSpec, cell_names: list[str],
                     ticks: int) -> list[dict]:
    """The cells fault schedule, trace-event shaped (rides the hash
    like the classic plan)."""
    events: list[dict] = []

    def window(kind: str, cell: str, at: int, dur: int,
               origin: str | None = None) -> None:
        ev: dict = {"tick": at, "op": "fault", "kind": kind,
                    "cell": cell}
        if origin:
            ev["origin"] = origin
        events.append(ev)
        events.append({"tick": at + dur, "op": "fault",
                       "kind": "cell-heal", "cell": cell})

    if spec.full_partition_at:
        window("cell-partition-full",
               cell_names[spec.full_partition_cell % len(cell_names)],
               spec.full_partition_at, spec.full_partition_ticks)
    if spec.asym_partition_at:
        window("cell-partition-asym",
               cell_names[spec.asym_partition_cell % len(cell_names)],
               spec.asym_partition_at, spec.asym_partition_ticks)
    if spec.straddle_at:
        # The straddle is a full partition of the DONOR, timed to
        # strand a pending capacity claim.  Its window is deliberately
        # NOT subject to the peer-unaffected check: the peer here is
        # the STARVED cell, whose whole point is that it cannot place
        # until the reclaim lands.
        window("cell-partition-full",
               cell_names[spec.donor_cell_default % len(cell_names)],
               spec.straddle_at, spec.straddle_ticks,
               origin="straddle")
    if spec.xcell_probe_at:
        t = spec.xcell_probe_at
        while t < ticks:
            events.append({"tick": t, "op": "fault",
                           "kind": "xcell-probe"})
            t += max(spec.xcell_probe_every, 1)
    events.sort(key=lambda e: e["tick"])
    return events


class ChaosCellCluster(ChaosCluster):
    """ChaosCluster + the partition fault family: per-cell verb
    swallowing and broadcast suppression, toggled by the engine.  The
    socket stays up throughout — a partition is silence, not a
    hangup."""

    RECLAIM_VERBS = frozenset({
        "claimCapacity", "offerCapacity", "listClaims",
    })

    def __init__(self, **kwargs) -> None:
        super().__init__(**kwargs)
        #: Cells currently FULLY partitioned: every request from their
        #: sessions is swallowed and no broadcast reaches them.
        self.full_partitioned: set[str] = set()
        #: Cells in the ASYMMETRIC (half-open) state: watch and lease
        #: verbs live, data-plane writes + reclaim verbs + the ping
        #: probe swallowed.
        self.asym_partitioned: set[str] = set()
        self.partition_swallowed = 0

    def _session_blocked(self, writer) -> bool:
        cell = self._session_cells.get(id(writer))
        return cell is not None and cell in self.full_partitioned

    def _handle(self, writer, msg: dict) -> None:
        cell = msg.get("cell")
        if cell is not None:
            if cell in self.full_partitioned:
                with self._lock:
                    self.partition_swallowed += 1
                    # Tag the session even while dark so broadcast
                    # suppression covers it from the first request.
                    self._session_cells[id(writer)] = str(cell)
                return
            if cell in self.asym_partitioned:
                verb = msg.get("verb")
                if verb in self.WRITE_VERBS or "path" in msg \
                        or verb in self.RECLAIM_VERBS:
                    with self._lock:
                        self.partition_swallowed += 1
                    return
        super()._handle(writer, msg)


class CellRuntime:
    """One cell's full scheduler stack (the per-cell analog of the
    classic engine's single wire state)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self.holder = f"{name}-sched"
        self.epoch: int | None = None
        self.have_lease = False
        self.lease_lost = False
        self.cache: SchedulerCache | None = None
        self.backend: StreamBackend | None = None
        self.adapter: WatchAdapter | None = None
        self.scheduler: Scheduler | None = None
        self.guardrails = None
        self.seam = None
        self.socks: list[socket.socket] = []
        self.sock: socket.socket | None = None
        #: Ticks the cell's pending demand has exceeded its total
        #: capacity (the structural-starvation clock).
        self.starved_ticks = 0
        self.claim_inflight: int | None = None
        self.claims_made = 0
        self.donations = 0
        self.stood_down = 0
        #: The cell's Autopilot (autopilot mode only) — replaces the
        #: manual claim/donor duties at the same per-tick duty site.
        self.autopilot = None
        self.ingest = {"events": 0, "batches": 0, "coalesced": 0}

    def harvest_ingest(self, adapter) -> None:
        self.ingest["events"] += getattr(adapter, "events_seen", 0)
        self.ingest["batches"] += getattr(adapter, "batches_applied", 0)
        self.ingest["coalesced"] += getattr(adapter, "coalesced_events", 0)


@dataclasses.dataclass
class CellChaosResult:
    ok: bool
    ticks_run: int
    violations: list[Violation]
    trace_hash: str
    final_assignment: dict[str, str]
    faults: dict[str, int]
    recoveries: dict[str, int]
    converged_tick: int | None
    dump_path: str | None
    cells: dict | None = None
    cross_cell: dict | None = None
    partitions: dict | None = None
    reclaim: dict | None = None
    ingest: dict | None = None
    trace: dict | None = None
    slo: dict | None = None
    autopilot: dict | None = None

    def summary(self) -> dict:
        return {
            "ok": self.ok,
            "ticks": self.ticks_run,
            "violations": [v.as_dict() for v in self.violations],
            "trace_hash": self.trace_hash,
            "bound_pods": len(self.final_assignment),
            "faults": dict(self.faults),
            "recoveries": dict(self.recoveries),
            "converged_after_drain_ticks": self.converged_tick,
            "flight_recorder": self.dump_path,
            "cells": self.cells,
            "cross_cell": self.cross_cell,
            "partitions": self.partitions,
            "reclaim": self.reclaim,
            "ingest": self.ingest,
            "trace": self.trace,
            "slo": self.slo,
            "autopilot": self.autopilot,
        }


class CellChaosEngine:
    """Drives N full scheduler stacks against one ChaosCellCluster,
    tick-deterministically (cells in sorted order within a tick)."""

    def __init__(
        self,
        seed: int = 0,
        ticks: int = 26,
        scenario: ScenarioSpec | None = None,
        cell_workloads: list[dict] | None = None,
        cell_faults: CellFaultSpec | None = None,
        conf_path: str | None = None,
        record: int = 64,
        drain: int = 60,
        dump_dir: str | None = None,
        quiesce_timeout: float = 30.0,
        ingest_mode: str | None = None,
        trace_obs: str | None = None,
        autopilot: str | None = None,
    ) -> None:
        from kube_batch_tpu.client.adapter import resolve_ingest_mode

        self.seed = seed
        self.ticks = ticks
        self.base_scenario = scenario or ScenarioSpec()
        self.cell_faults = cell_faults or CellFaultSpec()
        self.cell_names = [
            f"cell-{chr(ord('a') + i)}"
            for i in range(max(self.cell_faults.cells, 2))
        ]
        overrides = list(cell_workloads or [])
        while len(overrides) < len(self.cell_names):
            overrides.append({})
        self.cell_scenarios = [
            dataclasses.replace(self.base_scenario, **{
                k: tuple(tuple(x) if isinstance(x, list) else x
                         for x in v) if isinstance(v, list) else v
                for k, v in ov.items()
            })
            for ov in overrides[: len(self.cell_names)]
        ]
        self.conf_path = conf_path
        self.drain = drain
        self.dump_dir = dump_dir or tempfile.gettempdir()
        self.quiesce_timeout = quiesce_timeout
        self.ingest_mode = resolve_ingest_mode(ingest_mode)
        self.trace_obs = trace_obs or "on"
        if self.trace_obs not in ("on", "off"):
            raise ValueError(
                f"trace_obs must be 'on' or 'off', got {self.trace_obs!r}"
            )
        # Autopilot mode: the CLI flag overrides the scenario's
        # "autopilot" knob either way; OFF keeps the manual claim/
        # donor duties and must be decision-invisible (the off-parity
        # run in scripts/check_chaos_autopilot.py pins it).
        if autopilot is None:
            self.autopilot_mode = (
                "on" if self.cell_faults.autopilot else "off"
            )
        elif autopilot in ("on", "off"):
            self.autopilot_mode = autopilot
        else:
            raise ValueError(
                f"autopilot must be 'on' or 'off', got {autopilot!r}"
            )
        self.wire_timeout = (
            ASYM_WIRE_TIMEOUT if self.cell_faults.asym_partition_at
            else 10.0
        )
        self.recorder = FlightRecorder(keep=record)
        self.fault_counts: collections.Counter = collections.Counter()
        self.recovery_counts: collections.Counter = collections.Counter()
        self.cluster: ChaosCellCluster | None = None
        self.cells = [CellRuntime(n) for n in self.cell_names]
        self._decision_cursor = 0
        self._decisions: list[dict] = []
        #: tick -> {cell: accepted binds} (the peer-unaffected
        #: invariant reads the partition windows out of this).
        self._binds_by_tick: dict[int, collections.Counter] = {}
        #: Full-partition windows actually OPENED: cell -> [(t0, t1)].
        self._partition_windows: dict[str, list[list[int]]] = {}
        self._asym_cells_seen: set[str] = set()
        # Cross-cell probe accounting (engine-driven, deterministic).
        self._xcell_attempted = 0
        self._xcell_rejected = 0
        self._xcell_accepted = 0
        self._xcell_local_fenced = 0
        self._straddle_rollbacks = 0
        self._trace_dump_dir: str | None = None
        self._trace_summary: dict | None = None
        # -- SLO engine state (trace/slo.py; trace_obs == "on" only) ---
        #: uid -> submit tick (the placement series' arrival clock).
        self._arrival_ticks: dict[str, int] = {}
        self._placement_seen: set[str] = set()
        #: cell -> ticks its CYCLE objective read fast-burn.
        self._slo_flagged: dict[str, set[int]] = {}
        #: The /debug/fleet body captured the first tick a FULLY DARK
        #: cell read fast-burn — the acceptance evidence that the pane
        #: names the burning cell while its peer reads healthy.
        self._fleet_during_burn: dict | None = None
        self._slo_summary: dict | None = None
        #: Cross-scheduler stitched traces (computed while the tracers
        #: are still alive; cached — _check_cells and _teardown share
        #: it).
        self._stitched: dict | None = None

    # -- wiring ---------------------------------------------------------
    def _connect(self, rt: CellRuntime, replay: bool) -> None:
        a, b = socket.socketpair()
        cl_r = a.makefile("r", encoding="utf-8")
        cl_w = a.makefile("w", encoding="utf-8")
        sch_r = b.makefile("r", encoding="utf-8")
        sch_w = b.makefile("w", encoding="utf-8")
        self.cluster.attach(cl_r, cl_w)
        if not self.cluster._started:
            self.cluster.start()
        if replay:
            self.cluster.replay(cl_w)
        old = rt.adapter
        if rt.backend is None:
            rt.backend = StreamBackend(sch_w, timeout=self.wire_timeout)
            rt.backend.set_cell(rt.name)
        else:
            rt.backend.reconnect(sch_w)
        adapter = WatchAdapter(
            rt.cache, sch_r, backend=rt.backend,
            ingest_mode=self.ingest_mode, cell=rt.name,
        )
        if old is not None:
            adapter.resource_versions.update(old.resource_versions)
            adapter.list_rv = old.list_rv
            adapter.adopt_cell_topology(old)
            rt.harvest_ingest(old)
        adapter.start()
        rt.backend.cell_of_node = adapter.cell_of_node
        rt.socks.extend((a, b))
        rt.sock = b
        rt.adapter = adapter

    def _sever(self, rt: CellRuntime) -> None:
        try:
            rt.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        deadline = time.monotonic() + self.quiesce_timeout
        while not rt.adapter.stopped.wait(0.01):
            if time.monotonic() > deadline:
                raise ChaosEngineError(
                    f"{rt.name}: severed stream never stopped the "
                    "watch adapter"
                )

    def _reconnect(self, rt: CellRuntime) -> str:
        since = rt.adapter.latest_rv
        self._connect(rt, replay=False)
        mode = resume_session(
            rt.cache, rt.backend, rt.adapter, since,
            sync_timeout=self.quiesce_timeout,
        )
        self.recovery_counts[f"{mode}-{rt.name}"] += 1
        return mode

    def _quiesce(self, rt: CellRuntime) -> None:
        deadline = time.monotonic() + self.quiesce_timeout
        while time.monotonic() < deadline:
            if rt.adapter.stopped.is_set():
                return
            with self.cluster._lock:
                rv = self.cluster._rv
            if rt.adapter.synced.is_set() and rt.adapter.latest_rv >= rv:
                return
            time.sleep(0.002)
        raise ChaosEngineError(f"{rt.name}: ingest quiesce timed out")

    # -- leases ---------------------------------------------------------
    def _renew_lease(self, rt: CellRuntime, rec: dict) -> bool:
        try:
            if rt.have_lease:
                rt.backend.renew_lease(rt.holder, LEASE_TTL)
            else:
                rt.epoch = rt.backend.acquire_lease(rt.holder, LEASE_TTL)
                rt.backend.set_epoch(rt.epoch)
                rt.have_lease = True
                if rt.lease_lost:
                    rt.lease_lost = False
                    self.recovery_counts[f"lease-{rt.name}"] += 1
        except RuntimeError:
            rt.have_lease = False
            rt.lease_lost = True
            rec.setdefault("lease-lost", []).append(rt.name)
            return False
        except (ConnectionError, TimeoutError) as exc:
            with self.cluster._lock:
                dark = (rt.name in self.cluster.full_partitioned
                        or rt.name in self.cluster.asym_partitioned)
            if dark:
                # Partitioned: the lease verb was swallowed — stand
                # down for the tick, exactly what a real cell does
                # when its control plane goes unreachable.
                rec.setdefault("lease-unreachable", []).append(rt.name)
                return False
            raise ChaosEngineError(
                f"{rt.name}: lease verb failed on a live stream: {exc}"
            ) from exc
        return True

    # -- cross-cell reclaim duties --------------------------------------
    @staticmethod
    def _cache_demand(rt: CellRuntime) -> tuple[float, float, float]:
        """(pending_cpu, total_demand_cpu, alloc_cpu) from the cell's
        own mirror — the structural-starvation / affordability inputs."""
        with rt.cache.lock():
            alloc = sum(
                float(n.node.allocatable.get("cpu", 0.0))
                for n in rt.cache._nodes.values()
            )
            pending = total = 0.0
            for p in rt.cache._pods.values():
                cpu = float(p.request.get("cpu", 0.0))
                total += cpu
                if p.status == TaskStatus.PENDING:
                    pending += cpu
        return pending, total, alloc

    def _claim_duty(self, rt: CellRuntime, rec: dict) -> None:
        """The starved side: claim capacity from a donor once pending
        demand has structurally exceeded this cell's whole capacity
        for `reclaim_after_ticks` ticks and no claim is in flight."""
        spec = self.cell_faults
        if rt.claim_inflight is not None:
            with self.cluster._lock:
                claim = self.cluster.reclaim_claims.get(rt.claim_inflight)
            if claim is not None and claim["state"] != "pending":
                # Terminal: granted capacity arrives on the watch;
                # a rollback re-arms the claim duty after heal.
                # Outcome counters + recorder transitions ride along
                # (observation-only: neither is hashed).
                if claim["state"] == "rolled-back":
                    outcome = "rolled_back"
                elif claim.get("fractional"):
                    outcome = "expired"
                else:
                    outcome = "granted"
                metrics.note_reclaim_outcome(outcome)
                trace_obs_mod.note_transition(
                    "reclaim-resolve", claim=claim["id"],
                    cell=rt.name, outcome=outcome,
                )
                rec.setdefault("claims-resolved", []).append(
                    {"cell": rt.name, "claim": claim["id"],
                     "state": claim["state"]},
                )
                rt.claim_inflight = None
            else:
                return
        pending, _total, alloc = self._cache_demand(rt)
        if pending > alloc:
            rt.starved_ticks += 1
        else:
            rt.starved_ticks = 0
            return
        if rt.starved_ticks < max(spec.reclaim_after_ticks, 1):
            return
        donor = next(
            (n for n in self.cell_names if n != rt.name), None
        )
        if donor is None:
            return
        try:
            # The claim is the ORIGIN of a cross-scheduler flow: the
            # flow's traceparent rides the claimCapacity request, the
            # cluster remembers it on the claim, and the donor's
            # drain + offer stitch under the same trace id
            # (doc/design/observability.md).  A no-op flow when
            # tracing is off.
            with trace_obs_mod.flow(
                "reclaim-claim", cell=rt.name, donor=donor,
            ):
                resp = rt.backend._call({
                    "verb": "claimCapacity", "from": donor,
                    "ttlTicks": spec.reclaim_ttl_ticks,
                })
        except (ConnectionError, TimeoutError):
            return  # partitioned mid-claim: retried next tick
        rt.claim_inflight = int(resp.get("claim", 0)) or None
        rt.claims_made += 1
        self.fault_counts["reclaim-claim"] += 1
        trace_obs_mod.note_transition(
            "reclaim-claim", claim=rt.claim_inflight, cell=rt.name,
            donor=donor,
        )
        rec.setdefault("claims", []).append(
            {"cell": rt.name, "from": donor,
             "claim": rt.claim_inflight},
        )

    def _donor_duty(self, rt: CellRuntime, rec: dict) -> None:
        """The donor side: discover pending claims naming this cell
        (listClaims), free ONE node through the normal evict seam —
        gang-atomically: every placed member of every gang resident on
        the chosen node is evicted, so no gang is ever stranded
        half-on donated hardware — and offer it.  Refuses when the
        cell cannot afford the capacity loss."""
        try:
            resp = rt.backend._call({"verb": "listClaims"})
        except (ConnectionError, TimeoutError):
            return  # partitioned: the claim will roll back on TTL
        claims = [c for c in resp.get("object") or []
                  if c.get("state") == "pending"]
        if not claims:
            return
        claim = claims[0]  # one donation per tick keeps ticks bounded
        _pending, total, alloc = self._cache_demand(rt)
        with rt.cache.lock():
            nodes = sorted(
                (info.node for info in rt.cache._nodes.values()),
                key=lambda n: n.name,
            )
            residents: dict[str, list] = {n.name: [] for n in nodes}
            for p in rt.cache._pods.values():
                if p.node in residents and p.status in (
                    TaskStatus.BOUND, TaskStatus.RUNNING,
                    TaskStatus.BINDING,
                ):
                    residents[p.node].append(p)
        candidates = sorted(
            nodes, key=lambda n: (len(residents[n.name]), n.name)
        )
        for node in candidates:
            if total > alloc - float(node.allocatable.get("cpu", 0.0)):
                continue  # cannot afford to lose this node
            groups = sorted({
                p.group for p in residents[node.name] if p.group
            })
            with rt.cache.lock():
                victims = sorted(
                    (
                        p for p in rt.cache._pods.values()
                        if (p.group in groups or p in residents[node.name])
                        and p.node is not None
                        and p.status in (TaskStatus.BOUND,
                                         TaskStatus.RUNNING,
                                         TaskStatus.BINDING)
                    ),
                    key=lambda p: p.uid,
                )
            victim_nodes = {p.uid: p.node for p in victims}
            # The donor side of the stitched flow: adopt the
            # claimant's propagated context (the cluster handed it
            # back on listClaims), so the drain evictions and the
            # offer record as CHILD spans under the claim's trace id
            # — one Perfetto tree spanning both schedulers.
            from kube_batch_tpu.trace import context as trace_ctx

            parent = trace_ctx.parse(claim.get("traceparent"))
            try:
                with trace_obs_mod.flow(
                    "reclaim-donate", ctx=parent, cell=rt.name,
                    claim=claim["id"], node=node.name,
                ):
                    for pod in victims:
                        rt.seam.evict(pod, "reclaim-donate")
                    rt.backend._call({
                        "verb": "offerCapacity", "claim": claim["id"],
                        "node": node.name,
                    })
            except (ConnectionError, TimeoutError):
                return  # partitioned mid-donation: claim rolls back
            except RuntimeError as exc:
                log.warning("%s: donation refused: %s", rt.name, exc)
                return
            # The donor's decision story: a pod reclaimed across
            # cells must show the donor's drain eviction next to the
            # recipient's placement at /debug/pods/<uid> (the merged
            # fleet story) — the engine evicts through the raw seam,
            # which bypasses the cache's eviction funnel, so the
            # records land here.
            dlog = trace_obs_mod.decision_log()
            if dlog is not None:
                for pod in victims:
                    dlog.note_eviction(
                        pod.uid, pod.name, pod.group,
                        victim_nodes.get(pod.uid),
                        "reclaim-donate",
                        trace_obs_mod.current_cycle(),
                    )
            rt.donations += 1
            self.fault_counts["reclaim-grant"] += 1
            trace_obs_mod.note_transition(
                "reclaim-offer", claim=claim["id"], cell=rt.name,
                node=node.name, evicted=len(victims),
            )
            rec.setdefault("donations", []).append({
                "cell": rt.name, "claim": claim["id"],
                "node": node.name, "evicted": len(victims),
            })
            return
        log.info("%s: no affordable node to donate for claim %s",
                 rt.name, claim["id"])

    def _autopilot_duty(self, rt: CellRuntime, rec: dict) -> None:
        """Autopilot mode: one Autopilot.step() replaces the manual
        donor+claim duties at the same site — sense (publish the
        demand column), donate, resolve, decide.  The engine folds
        the step's record into its own tick record and fault
        counters so the summaries read the same either way."""
        out = rt.autopilot.step()
        claim = out.get("claim")
        if claim:
            rt.claim_inflight = claim["claim"]
            rt.claims_made += 1
            self.fault_counts["reclaim-claim"] += 1
            rec.setdefault("claims", []).append(
                {"cell": rt.name, **claim},
            )
        donation = out.get("donation")
        if donation:
            rt.donations += 1
            self.fault_counts["reclaim-grant"] += 1
            rec.setdefault("donations", []).append(
                {"cell": rt.name, **donation},
            )
        resolved = out.get("resolved")
        if resolved:
            rt.claim_inflight = None
            rec.setdefault("claims-resolved", []).append(
                {"cell": rt.name, **resolved},
            )
        for key in ("claim-error", "donate-skipped"):
            if out.get(key):
                rec.setdefault(f"autopilot-{key}", []).append(
                    {"cell": rt.name, "detail": out[key]},
                )

    # -- cross-cell zombie probes ---------------------------------------
    def _xcell_probe(self, rec: dict) -> None:
        """One live cell attempts cross-cell writes, both ways: raw
        through the wire (cluster fence must answer CellScope) and
        through the normal bind seam (the LOCAL cell fence must fail
        it without a wire round trip).  Deterministic: sorted cells,
        sorted pods, sorted nodes."""
        with self.cluster._lock:
            dark = self.cluster.full_partitioned | \
                self.cluster.asym_partitioned
        src = next(
            (rt for rt in self.cells
             if rt.name not in dark and rt.have_lease), None
        )
        if src is None:
            rec.setdefault("xcell-probe", []).append("skipped")
            return
        with self.cluster._lock:
            foreign = sorted(
                n for n in self.cluster.nodes
                if self.cluster.cell_of_node(n)
                not in ("", src.name)
            )
            own = sorted(
                uid for uid, p in self.cluster.pods.items()
                if self.cluster.cell_of_pod(p) == src.name
            )
            rejections_before = self.cluster.cross_cell_rejections
        if not foreign or not own:
            rec.setdefault("xcell-probe", []).append("skipped")
            return
        detail = {"cell": src.name, "node": foreign[0], "pod": own[0]}
        # Probe 1: the CLUSTER fence — a raw wire request, past the
        # local fence on purpose.
        self._xcell_attempted += 1
        try:
            src.backend._call({
                "verb": "bind", "pod": own[0], "node": foreign[0],
            })
            self._xcell_accepted += 1  # invariant violation
            detail["cluster"] = "ACCEPTED"
        except CellScopeError:
            self._xcell_rejected += 1
            detail["cluster"] = "rejected"
        except Exception as exc:  # noqa: BLE001 — a dead wire here is
            raise ChaosEngineError(   # a harness bug, not a fence test
                f"xcell probe failed outside the fence: {exc}"
            ) from exc
        # Probe 2: the LOCAL fence — the normal bind seam must fail
        # fast without the request ever reaching the wire.
        fake = types.SimpleNamespace(uid=own[0])
        try:
            src.backend.bind(fake, foreign[0])
            detail["local"] = "ACCEPTED"
            self._xcell_accepted += 1
        except CellScopeError:
            with self.cluster._lock:
                cluster_rejections = (
                    self.cluster.cross_cell_rejections
                    - rejections_before
                )
            if cluster_rejections <= 1:
                # Only probe 1 hit the cluster: probe 2 was fenced
                # locally, as designed.
                self._xcell_local_fenced += 1
                detail["local"] = "fenced-locally"
            else:
                detail["local"] = "rejected-on-wire"
        self.fault_counts["xcell-probe"] += 1
        rec.setdefault("xcell-probe", []).append(detail)

    # -- SLO engine feed + evaluation (trace/slo.py) --------------------
    def _feed_slo(self, t: int, rec: dict, cycled: set[str]) -> None:
        """Per-tick SLO feeding, deterministic: every cell that did
        NOT run a cycle this tick (fully dark, lease unreachable)
        feeds one synthetic bad cycle observation (a stood-down
        scheduler is an infinitely late cycle); placement observes
        pending-pod ages and first placements in ticks, from the
        cluster's authoritative state.  Then every engine evaluates —
        a fresh fast-burn breach auto-dumps an 'slo-burn' post-mortem
        into that cell's flight recorder."""
        if self.trace_obs != "on":
            return
        with self.cluster._lock:
            pods = [
                (self.cluster.cell_of_pod(p), uid, p.status)
                for uid, p in sorted(self.cluster.pods.items())
            ]
            dark_now = set(self.cluster.full_partitioned)
        placed = (TaskStatus.BOUND, TaskStatus.RUNNING)
        slo_rec: dict = {}
        for rt in self.cells:
            tracer = trace_obs_mod.get(scope=rt.name)
            if tracer is None or tracer.slo is None:
                continue
            engine = tracer.slo
            if rt.name not in cycled:
                engine.observe("cycle", CYCLE_SLO_BAD_VALUE)
            for cell, uid, status in pods:
                if cell != rt.name:
                    continue
                arrived = self._arrival_ticks.get(uid)
                if arrived is None:
                    continue
                age = float(t - arrived)
                if status == TaskStatus.PENDING:
                    if age > PLACEMENT_SLO_THRESHOLD_TICKS:
                        engine.observe("placement", age)
                elif status in placed and \
                        uid not in self._placement_seen:
                    self._placement_seen.add(uid)
                    engine.observe("placement", age)
            state = engine.evaluate()
            fast = state["cycle"]["fast_burn"]
            slo_rec[rt.name] = {
                "cycle_fast_burn": fast,
                "burn": state["cycle"]["burn"],
            }
            if fast:
                self._slo_flagged.setdefault(rt.name, set()).add(t)
                if self._fleet_during_burn is None and \
                        rt.name in dark_now:
                    # The acceptance evidence: ONE /debug/fleet body,
                    # captured while the dark cell burns — it must
                    # name the burning cell and show the peer healthy.
                    body = trace_obs_mod.debug_http("/debug/fleet")[1]
                    self._fleet_during_burn = {
                        "tick": t,
                        "burning_cell": rt.name,
                        "burning": (body.get("fleet") or {})
                        .get("burning"),
                        "cells": {
                            name: {
                                "state": blk.get("state"),
                                "fast_burning": sorted(
                                    (blk.get("slo") or {})
                                    .get("burning") or []
                                ),
                            }
                            for name, blk in
                            (body.get("cells") or {}).items()
                        },
                    }
        if slo_rec:
            rec["slo"] = slo_rec

    def _stitched_traces(self) -> dict:
        """Trace ids whose spans appear in ≥2 cells' tracers — the
        cross-scheduler stitching evidence (a reclaim's claim span in
        the starved cell, its drain+offer span in the donor, one
        trace id).  Computed while the tracers are alive; the merged
        Perfetto-loadable export is written next to the flight
        recorder dumps."""
        if self._stitched is not None:
            return self._stitched
        per_cell: dict[str, dict[str, list[dict]]] = {}
        for rt in self.cells:
            tracer = trace_obs_mod.get(scope=rt.name)
            if tracer is None:
                continue
            by_id: dict[str, list[dict]] = {}
            for ev in tracer.spans.chrome_events():
                tid = (ev.get("args") or {}).get("trace_id")
                if tid:
                    by_id.setdefault(tid, []).append(ev)
            per_cell[rt.name] = by_id
        all_ids: set[str] = set()
        for by_id in per_cell.values():
            all_ids.update(by_id)
        stitched: dict[str, dict] = {}
        for tid in sorted(all_ids):
            cells = sorted(c for c, by_id in per_cell.items()
                           if tid in by_id)
            if len(cells) >= 2:
                stitched[tid] = {
                    "cells": cells,
                    "spans": {
                        c: sorted(ev["name"]
                                  for ev in per_cell[c][tid])
                        for c in cells
                    },
                }
        export_path = None
        if stitched:
            events = []
            for cell, by_id in sorted(per_cell.items()):
                for tid, evs in sorted(by_id.items()):
                    if tid not in stitched:
                        continue
                    for ev in evs:
                        ev = dict(ev)
                        ev["args"] = {**(ev.get("args") or {}),
                                      "cell": cell}
                        events.append(ev)
            try:
                os.makedirs(self.dump_dir, exist_ok=True)
                export_path = os.path.join(
                    self.dump_dir,
                    f"chaos-cells-stitched-seed{self.seed}.json",
                )
                with open(export_path, "w", encoding="utf-8") as f:
                    json.dump({"traceEvents": events}, f, indent=1,
                              sort_keys=True)
                    f.write("\n")
            except OSError as exc:
                log.warning("stitched-trace export failed: %s", exc)
                export_path = None
        self._stitched = {
            "count": len(stitched),
            "traces": stitched,
            "export": export_path,
        }
        return self._stitched

    # -- partition faults -----------------------------------------------
    def _fire_fault(self, ev: dict, t: int, rec: dict) -> None:
        kind = ev["kind"]
        if kind in ("cell-partition-full", "cell-partition-asym"):
            cell = ev["cell"]
            with self.cluster._lock:
                if kind.endswith("full"):
                    self.cluster.full_partitioned.add(cell)
                    if ev.get("origin") != "straddle":
                        self._partition_windows.setdefault(
                            cell, []
                        ).append([t, self.ticks + self.drain])
                else:
                    self.cluster.asym_partitioned.add(cell)
                    self._asym_cells_seen.add(cell)
            self.fault_counts[kind] += 1
            rec.setdefault("faults", []).append(
                {"kind": kind, "cell": cell},
            )
        elif kind == "cell-heal":
            cell = ev["cell"]
            with self.cluster._lock:
                was_full = cell in self.cluster.full_partitioned
                self.cluster.full_partitioned.discard(cell)
                self.cluster.asym_partitioned.discard(cell)
            if was_full and self._partition_windows.get(cell) and \
                    self._partition_windows[cell][-1][1] == \
                    self.ticks + self.drain:
                self._partition_windows[cell][-1][1] = t
            rt = next(r for r in self.cells if r.name == cell)
            if was_full:
                # The dark window suppressed broadcasts: force the
                # resume path so the healed cell replays the missed
                # tail (or relists past a 410) before its next cycle.
                self._sever(rt)
                rec.setdefault("faults", []).append({
                    "kind": "cell-heal", "cell": cell,
                    "resume": self._reconnect(rt),
                })
            else:
                rec.setdefault("faults", []).append(
                    {"kind": "cell-heal", "cell": cell},
                )
            self.recovery_counts[f"heal-{cell}"] += 1
        elif kind == "xcell-probe":
            self._xcell_probe(rec)
        else:
            raise ChaosEngineError(f"unknown cell fault kind {kind!r}")

    # -- the run --------------------------------------------------------
    def _build_events(self) -> tuple[list[dict], list[dict]]:
        events: list[dict] = []
        for i, cell in enumerate(self.cell_names):
            evs = generate(
                self.cell_scenarios[i], self.seed * 10 + i, self.ticks
            )
            events.extend(cellify(evs, cell))
        spec = self.cell_faults
        if spec.asym_partition_at:
            # The half-open case needs the victim actually WRITING
            # into the black hole: a small gang lands in the asym cell
            # at the window's onset, so its bind dispatches time out
            # and the breaker must trip against a live watch.  It
            # places after heal — part of convergence like any gang.
            cell = self.cell_names[
                spec.asym_partition_cell % len(self.cell_names)
            ]
            group = f"asym-nudge-{self.seed}"
            events.append({
                "tick": spec.asym_partition_at, "op": "submit",
                "group": group, "queue": f"{cell}-default",
                "minMember": 3, "priority": 10,
                "pods": [
                    {
                        "name": f"{group}-{i}",
                        "uid": f"uid-{group}-{i}",
                        "group": group,
                        "priority": 10,
                        "request": {"cpu": 500.0, "memory": GI / 2,
                                    "pods": 1.0},
                    }
                    for i in range(3)
                ],
            })
        if spec.starve_pods and spec.starve_at:
            cell = self.cell_names[spec.starve_cell % len(self.cell_names)]
            group = f"starve-{self.seed}"
            events.append({
                "tick": spec.starve_at, "op": "submit", "group": group,
                "queue": f"{cell}-default",
                "minMember": spec.starve_pods, "priority": 50,
                "pods": [
                    {
                        "name": f"{group}-{i}",
                        "uid": f"uid-{group}-{i}",
                        "group": group,
                        "priority": 50,
                        "request": {
                            "cpu": spec.starve_cpu_milli,
                            "memory": spec.starve_mem_gi * GI,
                            "pods": 1.0,
                        },
                    }
                    for i in range(spec.starve_pods)
                ],
            })
        events.sort(key=lambda e: e["tick"])
        faults = plan_cell_faults(spec, self.cell_names, self.ticks)
        return events, faults

    def run(self) -> CellChaosResult:
        events, fault_events = self._build_events()
        by_tick: dict[int, list[dict]] = collections.defaultdict(list)
        for ev in events:
            by_tick[ev["tick"]].append(ev)
        faults_by_tick: dict[int, list[dict]] = collections.defaultdict(list)
        for ev in fault_events:
            faults_by_tick[ev["tick"]].append(ev)

        if self.trace_obs == "on":
            self._trace_dump_dir = tempfile.mkdtemp(
                prefix="kb-chaos-cells-trace-"
            )
            for rt in self.cells:
                trace_obs_mod.enable(
                    dump_dir=self._trace_dump_dir, scope=rt.name,
                )
        else:
            trace_obs_mod.disable()

        self.cluster = ChaosCellCluster(seed=self.seed, history=8192)
        if self.trace_obs == "on":
            # Tick-clocked SLO engines, one per cell's tracer: the
            # partitioned cell must FLAG fast-burn during its dark
            # window (and auto-dump an 'slo-burn' post-mortem) and
            # CLEAR after heal — engine invariants below.  Decision-
            # invisible: observations only; the same seed hashes
            # identically with the engines armed or not (the
            # --trace off parity run pins it).
            from kube_batch_tpu.trace.slo import SloEngine, SloObjective

            for ev in events:
                if ev.get("op") == "submit":
                    for pod in ev.get("pods", ()):
                        self._arrival_ticks[pod["uid"]] = ev["tick"]
            for rt in self.cells:
                tracer = trace_obs_mod.get(scope=rt.name)
                tracer.arm_slo(SloEngine(
                    [
                        # min_events 2: the tick clock feeds ~1
                        # observation per tick, so the production
                        # cold-start floor (10) would outlast the
                        # 3-tick fast window entirely.
                        SloObjective(
                            "cycle", "cycle", target=0.9,
                            threshold=CYCLE_SLO_THRESHOLD_S,
                            fast=SLO_FAST, slow=SLO_SLOW,
                            min_events=2,
                        ),
                        SloObjective(
                            "placement", "placement", target=0.9,
                            threshold=PLACEMENT_SLO_THRESHOLD_TICKS,
                            fast=SLO_FAST, slow=SLO_SLOW,
                            min_events=2,
                        ),
                    ],
                    clock=lambda: float(self.cluster.tick_now),
                ))
        from kube_batch_tpu.guardrails import GuardrailConfig, Guardrails

        for rt in self.cells:
            rt.cache = SchedulerCache(
                spec=ResourceSpec(),
                binder=None, evictor=None, status_updater=None,
                default_queue=f"{rt.name}-default",
            )
            self._connect(rt, replay=True)
            rt.guardrails = Guardrails(GuardrailConfig(
                hbm_ceiling_mb=None,
                watchdog_overruns=GUARDRAIL_ENGAGE_AFTER,
                watchdog_recovery=GUARDRAIL_RECOVER_AFTER,
                watchdog_period=GUARDRAIL_WATCHDOG_PERIOD,
                breaker_failures=GUARDRAIL_TRIP_AFTER,
                breaker_reset_s=GUARDRAIL_RESET_TICKS,
                backoff_base_s=0.01,
                backoff_cap_s=0.04,
                backoff_attempts=2,
            ), scope=rt.name)
            rt.seam = rt.guardrails.guard_backend(
                rt.backend, rt.cache, name=f"wire-{rt.name}",
                clock=lambda: float(self.cluster.tick_now),
            )
            rt.cache.binder = rt.seam
            rt.cache.evictor = rt.seam
            rt.cache.status_updater = rt.seam
            if not rt.adapter.wait_for_sync(self.quiesce_timeout):
                raise ChaosEngineError(
                    f"{rt.name}: initial LIST replay never synced"
                )
            with scope.bound(rt.name):
                rt.scheduler = Scheduler(
                    rt.cache, conf_path=self.conf_path,
                    schedule_period=0.0, guardrails=rt.guardrails,
                )
            if self.autopilot_mode == "on":
                # The engine drives the Autopilot at the duty site
                # (one_tick), NOT via Scheduler.run_once — the duties
                # must run BEFORE the tick's cycle, exactly where the
                # manual claim/donor duties ran, so autopilot-off
                # stays byte-identical.
                from kube_batch_tpu.autopilot import (
                    Autopilot, AutopilotConfig,
                )

                spec = self.cell_faults
                rt.autopilot = Autopilot(
                    cache=rt.cache, backend=rt.backend, cell=rt.name,
                    config=AutopilotConfig(
                        mode="on",
                        donors=tuple(n for n in self.cell_names
                                     if n != rt.name),
                        arm_after=spec.autopilot_arm_after,
                        quiet_after=spec.autopilot_quiet_after,
                        cooldown_ticks=spec.autopilot_cooldown_ticks,
                        claim_ttl_ticks=spec.reclaim_ttl_ticks,
                        max_nodes_per_claim=spec.autopilot_max_nodes,
                        headroom_cpu_milli=(
                            spec.autopilot_headroom_cpu_milli
                        ),
                        require_slo_burn=(self.trace_obs == "on"),
                        slo_objective="placement",
                        burn_memory=spec.autopilot_burn_memory,
                    ),
                    evict=rt.seam.evict,
                    slo=(lambda rt=rt: getattr(
                        trace_obs_mod.get(scope=rt.name), "slo", None,
                    )),
                )

        checker = InvariantChecker(self.cluster)
        violations: list[Violation] = []
        converged_tick: int | None = None
        ticks_run = 0

        def one_tick(t: int, active: bool) -> list[Violation]:
            nonlocal ticks_run
            self.cluster.tick_now = t
            self.cluster.claim_clock = t
            rec: dict = {"tick": t}
            # Drain ticks inject nothing new, but HEALS still fire: a
            # partition window reaching past the horizon must lift
            # during the drain, or the dark cell can never converge.
            fault_list = faults_by_tick.get(t, ())
            if not active:
                fault_list = [
                    fe for fe in fault_list if fe["kind"] == "cell-heal"
                ]
            for fe in fault_list:
                self._fire_fault(fe, t, rec)
            # Claims past deadline roll back — straddle accounting
            # reads whether the DONOR was dark at rollback time.
            with self.cluster._lock:
                dark_now = set(self.cluster.full_partitioned)
            before = {
                cid: c["from"]
                for cid, c in self.cluster.reclaim_claims.items()
                if c["state"] == "pending"
            }
            rolled = self.cluster.expire_reclaims()
            if rolled:
                rec["reclaim-rollbacks"] = rolled
                self.fault_counts["reclaim-rollback"] += rolled
                for cid, donor in before.items():
                    claim = self.cluster.reclaim_claims[cid]
                    if claim["state"] == "rolled-back" and \
                            donor in dark_now:
                        self._straddle_rollbacks += 1
            evs = by_tick.get(t, ())
            if not active:
                evs = [e for e in evs if e["op"] == "complete"]
            for ev in evs:
                apply_to_cluster(self.cluster, ev)
            rec["workload"] = len(evs)
            cycled: set[str] = set()
            for rt in self.cells:
                with self.cluster._lock:
                    fully_dark = rt.name in self.cluster.full_partitioned
                if fully_dark:
                    rt.stood_down += 1
                    rec.setdefault("stood-down", []).append(rt.name)
                    continue
                with scope.bound(rt.name):
                    if rt.adapter.stopped.is_set() or \
                            rt.backend.closed.is_set():
                        rec[f"reconnect-{rt.name}"] = self._reconnect(rt)
                    lead = self._renew_lease(rt, rec)
                    self._quiesce(rt)
                    if lead:
                        if rt.autopilot is not None:
                            self._autopilot_duty(rt, rec)
                        else:
                            self._donor_duty(rt, rec)
                            self._claim_duty(rt, rec)
                        # The duties' wire effects (drain evictions,
                        # the grant's node re-cell) come back as watch
                        # events: quiesce AGAIN so the solve's
                        # snapshot deterministically includes them —
                        # otherwise whether this tick's cycle sees
                        # the freed pods is a thread race and the
                        # same seed hashes differently.
                        self._quiesce(rt)
                        rt.scheduler.run_once()
                        cycled.add(rt.name)
                    else:
                        rt.stood_down += 1
            self.cluster.tick()
            for rt in self.cells:
                with self.cluster._lock:
                    if rt.name in self.cluster.full_partitioned:
                        continue
                self._quiesce(rt)
            self._feed_slo(t, rec, cycled)
            found = self._drain_decisions(t, rec)
            found += checker.check_tick(t)
            if found:
                rec["violations"] = [v.as_dict() for v in found]
                for v in found:
                    metrics.chaos_invariant_violations.inc(v.kind)
            self.recorder.record(rec)
            ticks_run += 1
            return found

        try:
            for t in range(self.ticks):
                violations = one_tick(t, active=True)
                if violations:
                    break
            else:
                for extra in range(self.drain):
                    t = self.ticks + extra
                    violations = one_tick(t, active=False)
                    if violations:
                        break
                    if self._all_settled() and self._cells_recovered():
                        converged_tick = extra
                        break
                else:
                    violations = checker.pending_after_deadline(
                        self.ticks + self.drain
                    )
                if not violations:
                    violations = self._check_cells(ticks_run)
        finally:
            self._teardown()

        final = self._final_assignment()
        full_hash = trace_hash(events + fault_events + self._decisions)
        dump_path = None
        if violations:
            os.makedirs(self.dump_dir, exist_ok=True)
            dump_path = os.path.join(
                self.dump_dir, f"chaos-cells-flight-seed{self.seed}.json",
            )
            self.recorder.dump(dump_path, meta={
                "seed": self.seed,
                "ticks": ticks_run,
                "violations": [v.as_dict() for v in violations],
                "trace_hash": full_hash,
            })
            log.error(
                "chaos-cells: %d invariant violation(s); flight "
                "recorder dumped to %s", len(violations), dump_path,
            )
        return CellChaosResult(
            ok=not violations,
            ticks_run=ticks_run,
            violations=list(violations),
            trace_hash=full_hash,
            final_assignment=final,
            faults=dict(self.fault_counts),
            recoveries=dict(self.recovery_counts),
            converged_tick=converged_tick,
            dump_path=dump_path,
            cells=self._cells_summary(),
            cross_cell=self._cross_cell_summary(),
            partitions=self._partitions_summary(),
            reclaim=self._reclaim_summary(),
            ingest=self._ingest_summary(),
            trace=self._trace_summary,
            slo=self._slo_summary,
            autopilot=self._autopilot_summary(),
        )

    # -- per-tick decision drain + cross-cell audit ---------------------
    def _drain_decisions(self, t: int, rec: dict) -> list[Violation]:
        with self.cluster._lock:
            tail = self.cluster.wire_log[self._decision_cursor:]
            self._decision_cursor = len(self.cluster.wire_log)
        tail = sorted(
            tail, key=lambda e: (e["op"], e.get("uid") or "",
                                 e.get("node") or "", e.get("claim") or 0),
        )
        out: list[Violation] = []
        binds = collections.Counter()
        for e in tail:
            if e["op"] != "bind":
                continue
            cell = e.get("cell")
            if cell:
                binds[cell] += 1
                with self.cluster._lock:
                    node_cell = self.cluster.cell_of_node(e["node"])
                if node_cell and node_cell != cell:
                    # Re-cells only happen in the pre-cycle donor
                    # phase, so a bind's node cell at drain time IS
                    # its cell at acceptance.
                    out.append(Violation(
                        "cross-cell-write-accepted", t,
                        f"bind of {e['uid']} by cell {cell!r} landed "
                        f"on node {e['node']!r} of cell {node_cell!r}",
                    ))
        if binds:
            self._binds_by_tick[t] = binds
        if tail:
            rec["decisions"] = tail
            self._decisions.extend(tail)
        return out

    # -- convergence ----------------------------------------------------
    def _all_settled(self) -> bool:
        with self.cluster._lock:
            if self.cluster.full_partitioned:
                return False  # a dark cell cannot have converged
            return all(
                p.status in (TaskStatus.BOUND, TaskStatus.RUNNING)
                for p in self.cluster.pods.values()
            )

    def _cells_recovered(self) -> bool:
        from kube_batch_tpu.guardrails import CircuitBreaker

        with self.cluster._lock:
            pending_claims = any(
                c["state"] == "pending"
                for c in self.cluster.reclaim_claims.values()
            )
        if pending_claims:
            return False
        return all(
            rt.guardrails.breaker_state() != CircuitBreaker.OPEN
            for rt in self.cells
        )

    # -- post-run invariants --------------------------------------------
    def _check_cells(self, tick: int) -> list[Violation]:
        out: list[Violation] = []
        spec = self.cell_faults
        # Cross-cell fencing actually exercised, nothing accepted.
        if spec.xcell_probe_at:
            if self._xcell_attempted < 1 or self._xcell_rejected < 1:
                out.append(Violation(
                    "xcell-fence-not-exercised", tick,
                    "no cross-cell write was attempted and rejected — "
                    "the cell-scope fence went untested",
                ))
            if self._xcell_local_fenced < 1:
                out.append(Violation(
                    "xcell-local-fence-not-exercised", tick,
                    "the client-side cell fence never fast-failed a "
                    "probe",
                ))
        if self._xcell_accepted:
            out.append(Violation(
                "cross-cell-write-accepted", tick,
                f"{self._xcell_accepted} cross-cell probe write(s) "
                "were ACCEPTED — no-cross-cell-write-accepted broken",
            ))
        # Partition shapes all fired.
        if spec.full_partition_at and \
                self.fault_counts.get("cell-partition-full", 0) < 1:
            out.append(Violation(
                "partition-not-fired", tick,
                "full_partition_at configured but never fired",
            ))
        if spec.asym_partition_at and \
                self.fault_counts.get("cell-partition-asym", 0) < 1:
            out.append(Violation(
                "partition-not-fired", tick,
                "asym_partition_at configured but never fired",
            ))
        # The asym (half-open) case must actually trip the victim's
        # breaker against a live watch — and it must have healed.
        if spec.asym_partition_at:
            for cell in sorted(self._asym_cells_seen):
                rt = next(r for r in self.cells if r.name == cell)
                breaker = rt.guardrails.breaker
                if breaker is None or breaker.opened_count < 1:
                    out.append(Violation(
                        "asym-breaker-never-tripped", tick,
                        f"{cell}: writes were black-holed with the "
                        "watch live but the wire breaker never "
                        "tripped",
                    ))
                elif breaker.closed_count < 1:
                    out.append(Violation(
                        "asym-breaker-never-closed", tick,
                        f"{cell}: breaker tripped but never healed "
                        "after the partition lifted",
                    ))
        # Peer-unaffected: during every full-partition window the
        # OTHER cells kept placing.
        for cell, windows in sorted(self._partition_windows.items()):
            for t0, t1 in windows:
                peer_binds = sum(
                    n
                    for t in range(t0, t1)
                    for c, n in self._binds_by_tick.get(
                        t, collections.Counter()
                    ).items()
                    if c != cell
                )
                if peer_binds < 1:
                    out.append(Violation(
                        "partitioned-cell-peer-starved", tick,
                        f"cell {cell!r} was dark over ticks "
                        f"[{t0},{t1}) and NO peer cell placed "
                        "anything — the partition leaked across the "
                        "cell boundary",
                    ))
        # Reclaim: atomic or rolled back; exercised when configured.
        with self.cluster._lock:
            claims = [dict(c) for c in
                      self.cluster.reclaim_claims.values()]
        unresolved = [c for c in claims if c["state"] == "pending"]
        if unresolved:
            out.append(Violation(
                "reclaim-unresolved", tick,
                f"{len(unresolved)} capacity claim(s) still pending "
                "after the drain — neither granted nor rolled back",
            ))
        for c in claims:
            if c["state"] == "rolled-back" and c["node"] is not None:
                out.append(Violation(
                    "reclaim-not-atomic", tick,
                    f"rolled-back claim {c['id']} still names node "
                    f"{c['node']!r} — capacity leaked into limbo",
                ))
            if c["state"] == "granted":
                # EVERY granted node (multi-node claims fill a list;
                # single-node claims carry just c["node"]) must live
                # in the claimant's cell.
                granted = c.get("granted") or [c["node"]]
                for node_name in granted:
                    with self.cluster._lock:
                        now_cell = self.cluster.cell_of_node(node_name)
                    if now_cell != c["to"]:
                        out.append(Violation(
                            "reclaim-not-atomic", tick,
                            f"granted claim {c['id']}: node "
                            f"{node_name!r} is in cell {now_cell!r}, "
                            f"not the claimant {c['to']!r}",
                        ))
        if spec.starve_pods:
            if not any(c["state"] == "granted" for c in claims):
                out.append(Violation(
                    "reclaim-never-granted", tick,
                    "starvation was injected but no capacity claim "
                    "was ever granted",
                ))
        if spec.straddle_at and self._straddle_rollbacks < 1:
            out.append(Violation(
                "straddle-not-exercised", tick,
                "a straddle partition was configured but no claim "
                "rolled back while its donor was dark",
            ))
        out.extend(self._check_slo_and_stitching(tick))
        return out

    def _check_slo_and_stitching(self, tick: int) -> list[Violation]:
        """The fleet-observability invariants (trace_obs == "on"
        runs only): the partitioned cell's SLO engine flagged
        fast-burn during its dark window, auto-dumped an 'slo-burn'
        post-mortem, and cleared after heal; /debug/fleet named the
        burning cell while its peer read healthy; and the reclaim
        produced ≥1 stitched trace whose span tree crosses both
        schedulers under one trace id."""
        if self.trace_obs != "on":
            return []
        out: list[Violation] = []
        spec = self.cell_faults
        # Fast burn flagged during every (non-straddle) dark window.
        for cell, windows in sorted(self._partition_windows.items()):
            flagged = self._slo_flagged.get(cell, set())
            for t0, t1 in windows:
                if not any(t0 <= ft <= t1 + SLO_FLAG_GRACE_TICKS
                           for ft in flagged):
                    out.append(Violation(
                        "slo-burn-not-flagged", tick,
                        f"cell {cell!r} was fully dark over "
                        f"[{t0},{t1}) but its SLO engine never read "
                        "fast-burn during the window",
                    ))
        # ... and CLEARED by the end of the drain.
        for rt in self.cells:
            tracer = trace_obs_mod.get(scope=rt.name)
            if tracer is None or tracer.slo is None:
                continue
            # Cleared = the deterministic CYCLE objective (the
            # placement objective keeps honestly burning right up to
            # the late placements a reclaim unblocks — that is the
            # SLO telling the truth, not a failure to clear).
            if "cycle" in tracer.slo.burning():
                out.append(Violation(
                    "slo-burn-not-cleared", tick,
                    f"{rt.name}: the cycle objective still reads "
                    "fast-burn after heal + drain — the burn never "
                    "cleared",
                ))
            # A fresh fast-burn breach must have auto-dumped a
            # post-mortem with trigger 'slo-burn' (rate-limited, so
            # one per cell suffices).
            if self._slo_flagged.get(rt.name) and not any(
                d.get("trigger") == "slo-burn"
                for d in tracer.recorder.dumps
            ):
                out.append(Violation(
                    "slo-burn-dump-missing", tick,
                    f"{rt.name}: fast-burn was flagged but no "
                    "'slo-burn' flight-recorder post-mortem was "
                    "auto-dumped",
                ))
        # The fleet pane named the burning cell while peers read
        # healthy (captured live, during the dark window).
        if self._partition_windows:
            snap = self._fleet_during_burn
            if snap is None:
                out.append(Violation(
                    "slo-fleet-snapshot-missing", tick,
                    "a cell burned while dark but no /debug/fleet "
                    "snapshot was captured",
                ))
            else:
                victim = snap["burning_cell"]
                vic = (snap["cells"].get(victim) or {})
                if "cycle" not in (vic.get("fast_burning") or []):
                    out.append(Violation(
                        "slo-fleet-burn-missing", tick,
                        f"/debug/fleet did not report cell {victim!r} "
                        f"burning during its dark window: {snap}",
                    ))
                for name, blk in sorted(snap["cells"].items()):
                    if name in ("", victim):
                        continue
                    # The deterministic objective is CYCLE (a live
                    # peer always cycles); the placement objective is
                    # workload-shaped and informational.
                    if "cycle" in (blk.get("fast_burning") or []):
                        out.append(Violation(
                            "slo-peer-burning", tick,
                            f"/debug/fleet showed PEER cell {name!r} "
                            "fast-burning during the victim's dark "
                            f"window: {snap}",
                        ))
        # Cross-scheduler stitching: the reclaim must leave ≥1 trace
        # whose span tree crosses both schedulers.
        if spec.starve_pods:
            stitched = self._stitched_traces()
            if stitched["count"] < 1:
                out.append(Violation(
                    "trace-not-stitched", tick,
                    "cross-cell reclaim ran but no trace id appears "
                    "in BOTH schedulers' span trees — stitching is "
                    "broken",
                ))
        return out

    # -- summaries ------------------------------------------------------
    def _cells_summary(self) -> dict:
        out = {}
        for rt in self.cells:
            out[rt.name] = {
                "epoch": int(rt.epoch or 0),
                "stood_down_ticks": rt.stood_down,
                "claims_made": rt.claims_made,
                "donations": rt.donations,
                "breaker_opened": (
                    rt.guardrails.breaker.opened_count
                    if rt.guardrails and rt.guardrails.breaker else 0
                ),
            }
            if rt.autopilot is not None:
                out[rt.name]["autopilot"] = {
                    "rung": rt.autopilot.ladder.rung,
                    "transitions": rt.autopilot.ladder.transitions,
                    "last_transition":
                        rt.autopilot.ladder.last_transition,
                    **rt.autopilot.counters,
                    "demand": (
                        rt.autopilot.last_signal.as_dict()
                        if rt.autopilot.last_signal else None
                    ),
                }
        return out

    def _cross_cell_summary(self) -> dict:
        return {
            "attempted": self._xcell_attempted,
            "rejected": self._xcell_rejected,
            "accepted": self._xcell_accepted,
            "local_fenced": self._xcell_local_fenced,
            "cluster_rejections": (
                self.cluster.cross_cell_rejections
                if self.cluster else 0
            ),
        }

    def _partitions_summary(self) -> dict:
        return {
            "full": self.fault_counts.get("cell-partition-full", 0),
            "asym": self.fault_counts.get("cell-partition-asym", 0),
            "swallowed": (
                self.cluster.partition_swallowed if self.cluster else 0
            ),
            "windows": {
                cell: [list(w) for w in ws]
                for cell, ws in sorted(self._partition_windows.items())
            },
            "straddle_rollbacks": self._straddle_rollbacks,
            # The straddle's dark window [t0, t1) — the autopilot
            # check script asserts zero claims were CREATED strictly
            # inside it (the ladder must not spam a dark donor).
            "straddle_window": (
                [self.cell_faults.straddle_at,
                 self.cell_faults.straddle_at
                 + self.cell_faults.straddle_ticks]
                if self.cell_faults.straddle_at else None
            ),
        }

    def _reclaim_summary(self) -> dict:
        with self.cluster._lock:
            claims = [dict(c) for c in
                      self.cluster.reclaim_claims.values()]
        return {
            "claims": len(claims),
            "granted": sum(1 for c in claims
                           if c["state"] == "granted"),
            "rolled_back": sum(1 for c in claims
                               if c["state"] == "rolled-back"),
            "pending": sum(1 for c in claims
                           if c["state"] == "pending"),
            "fractional": sum(1 for c in claims
                              if c.get("fractional")),
            "sequence": sorted(claims, key=lambda c: c["id"]),
        }

    def _autopilot_summary(self) -> dict:
        out: dict = {"mode": self.autopilot_mode}
        if self.autopilot_mode == "on":
            out["cells"] = {
                rt.name: {
                    "rung": rt.autopilot.ladder.rung,
                    "transitions": rt.autopilot.ladder.transitions,
                    **rt.autopilot.counters,
                }
                for rt in self.cells if rt.autopilot is not None
            }
        return out

    def _ingest_summary(self) -> dict:
        totals = {"events": 0, "batches": 0, "coalesced": 0}
        dropped = 0
        for rt in self.cells:
            if rt.adapter is not None:
                rt.harvest_ingest(rt.adapter)
                dropped += rt.adapter.cell_dropped
            for k in totals:
                totals[k] += rt.ingest[k]
        return {"mode": self.ingest_mode, "cell_filtered": dropped,
                **totals}

    def _final_assignment(self) -> dict[str, str]:
        with self.cluster._lock:
            return {
                uid: p.node
                for uid, p in sorted(self.cluster.pods.items())
                if p.node is not None
            }

    def _teardown(self) -> None:
        if self.trace_obs == "on":
            stitched = self._stitched_traces()
            per_cell = {}
            slo_cells = {}
            for rt in self.cells:
                tracer = trace_obs_mod.get(scope=rt.name)
                if tracer is not None:
                    per_cell[rt.name] = {
                        "spans_recorded":
                            tracer.spans.stats()["spans_recorded"],
                        "decision_records":
                            tracer.decisions.stats()["records_total"],
                        "dumps": [dict(d) for d in
                                  tracer.recorder.dumps],
                    }
                    if tracer.slo is not None:
                        state = tracer.slo.state()
                        slo_cells[rt.name] = {
                            "flagged_ticks": sorted(
                                self._slo_flagged.get(rt.name, ())
                            ),
                            "still_burning": tracer.slo.burning(),
                            "breaches": {
                                name: st.get("breaches", 0)
                                for name, st in
                                state["objectives"].items()
                            },
                            "slo_burn_dumps": sum(
                                1 for d in tracer.recorder.dumps
                                if d.get("trigger") == "slo-burn"
                            ),
                        }
                trace_obs_mod.disable(scope=rt.name)
            self._trace_summary = {
                "enabled": True, "cells": per_cell,
                "stitched": stitched,
            }
            self._slo_summary = {
                "cells": slo_cells,
                "fleet_during_burn": self._fleet_during_burn,
            }
        else:
            self._trace_summary = {"enabled": False}
            self._slo_summary = None
        if self._trace_dump_dir is not None:
            import shutil

            shutil.rmtree(self._trace_dump_dir, ignore_errors=True)
        metrics.reset_health_scopes()
        if self.cluster is not None:
            with self.cluster._lock:
                self.cluster.full_partitioned.clear()
                self.cluster.asym_partitioned.clear()
        for rt in self.cells:
            try:
                if rt.have_lease and rt.backend is not None:
                    rt.backend.release_lease(rt.holder)
            except Exception:  # noqa: BLE001 — best effort on the way down
                pass
            for sock in rt.socks:
                try:
                    sock.close()
                except OSError:
                    pass
