"""Post-tick invariant checking over the authoritative cluster.

The checker owns a placement model it replays from the ChaosCluster's
structured wire log (bind / evict / unplace / pod-gone entries) and
cross-checks against the cluster's pod/node truth after every simulated
tick.  Checked invariants:

1. **no-double-bind** — a bind accepted for a pod the model already
   holds placed (with no intervening unplacement) is a double bind:
   the scheduler committed the same task twice.  The companion
   **commit-order** check catches the pipelined-commit reordering
   hazard: an injected first-attempt bind-fault arriving AFTER an
   accepted bind for the same pod means a retry overtook its first
   attempt on the wire (per-pod write order broken).
2. **gang-readiness** — the first tick any member of a gang receives a
   bind attempt, the scheduler must have attempted at least
   ``min_member`` placements for that gang (attempts = accepted binds
   + injected bind faults; injected failures are the backend's doing,
   not a gang-gate violation).  A partial first wave means a
   non-Ready gang leaked through the JobReady gate.
3. **capacity** — per node, the summed requests of its placed pods
   never exceed allocatable in any resource dimension.
4. **eviction-accounting** — every eviction targets a pod that was
   actually placed, and the pod is observably unplaced (Pending or
   gone) afterwards; nothing evicts into thin air and no evicted pod
   silently keeps its node.
5. **convergence** (engine-driven, `pending_after_deadline`) — after
   the scenario quiesces, no admissible pod may stay Pending past the
   drain deadline.
6. **node health** (engine-driven, `engine._check_health_tick` /
   `_check_flaky` — they need the per-tick ledger samples this module
   does not hold): no-placement-on-cordoned,
   probation-canary-bounded, gang-atomic-drain, quarantine-engages
   and convergence-after-heal.  This module's only contribution is
   counting ``flaky-bind-fault`` entries as gang ATTEMPTS for the
   first-wave check — a refusal is the backend's doing, not a gang
   gate leak.
7. **no-stale-epoch-write-accepted / single-writer-per-epoch** — the
   log carries every lease-epoch mint (``epoch-advance`` entries) and
   every accepted write's stamping epoch: an accepted bind/evict whose
   epoch is not the one current AT ACCEPTANCE means a deposed
   leader's zombie write mutated the world — the split-brain
   corruption the epoch fence exists to prevent.  ``stale-reject``
   entries are the fence WORKING and replay as no-ops.

Violations are values, not exceptions: the engine decides to dump the
flight recorder and exit non-zero.
"""

from __future__ import annotations

import dataclasses

from kube_batch_tpu.api.types import TaskStatus

#: Float slack for capacity sums (requests are floats; the scheduler's
#: own fit test uses resource-spec epsilons far coarser than this).
EPS = 1e-6


@dataclasses.dataclass(frozen=True)
class Violation:
    kind: str
    tick: int
    message: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class InvariantChecker:
    """Replays the ChaosCluster wire log incrementally; `check_tick`
    is called once per simulated tick with the cluster quiesced."""

    def __init__(self, cluster) -> None:
        self.cluster = cluster
        self._log_cursor = 0
        # uid → node, the model's view of current placements.
        self._placed: dict[str, str] = {}
        # group → uids ever placed (for gang first-wave detection).
        self._group_placed: dict[str, set[str]] = {}
        # The lease epoch current at this point of the log replay,
        # PER CELL (advanced by epoch-advance entries; 0 = no lease
        # yet).  Key "" is the classic single-fleet lease; an entry
        # with no cell stamp replays against it — pre-cell scenarios
        # behave exactly as before.
        self._epochs: dict[str, int] = {"": 0}

    # -- per-tick -------------------------------------------------------
    def check_tick(self, tick: int) -> list[Violation]:
        cluster = self.cluster
        with cluster._lock:
            entries = cluster.wire_log[self._log_cursor:]
            self._log_cursor = len(cluster.wire_log)
            pods = {
                uid: (p.group, p.status, p.node, dict(p.request))
                for uid, p in cluster.pods.items()
            }
            nodes = {
                name: dict(n.allocatable)
                for name, n in cluster.nodes.items()
            }
            min_member = {
                name: g.min_member for name, g in cluster.groups.items()
            }
        violations: list[Violation] = []
        violations += self._replay_log(tick, entries, pods, min_member)
        violations += self._check_capacity(tick, pods, nodes)
        return violations

    # -- 1 + 2 + 4: log replay -----------------------------------------
    def _replay_log(self, tick, entries, pods, min_member):
        violations: list[Violation] = []
        # Gang first-wave accounting: attempts per group among THIS
        # batch of entries (one engine tick = one scheduling cycle).
        attempts: dict[str, int] = {}
        placed_before = {
            g: len(uids) for g, uids in self._group_placed.items()
        }
        first_wave: set[str] = set()
        for e in entries:
            op, uid, group = e["op"], e.get("uid"), e.get("group")
            cell = str(e.get("cell") or "")
            if op == "epoch-advance":
                self._epochs[cell] = int(e["epoch"])
                continue
            if op in ("stale-reject", "cell-reject"):
                continue  # the fences working: rejected, nothing mutated
            if op.startswith("reclaim-"):
                continue  # negotiation bookkeeping, replayed elsewhere
            if op in ("bind", "evict") and e.get("epoch") is not None \
                    and int(e["epoch"]) != self._epochs.get(cell, 0):
                # An ACCEPTED write stamped with a non-current epoch
                # OF ITS CELL: a zombie from a deposed leadership
                # mutated the world (the log is appended under the
                # cluster lock, so the epoch current at acceptance is
                # exactly the last epoch-advance replayed before this
                # entry — per cell: single-writer-per-CELL-epoch).
                violations.append(Violation(
                    "stale-epoch-write-accepted", tick,
                    f"{op} of pod {uid} accepted with epoch "
                    f"{e['epoch']} while epoch "
                    f"{self._epochs.get(cell, 0)} was current for "
                    f"cell {cell!r} — single-writer-per-cell-epoch "
                    "broken",
                ))
            if op in ("bind", "bind-fault", "flaky-bind-fault") and \
                    group is not None:
                # Refusals count as gang ATTEMPTS (the scheduler did
                # dispatch min_member placements; the backend — cursed
                # or flaky — refused them); only accepted binds place.
                attempts[group] = attempts.get(group, 0) + 1
                if placed_before.get(group, 0) == 0 and \
                        group not in first_wave:
                    first_wave.add(group)
            if op == "bind-fault" and uid in self._placed:
                # Per-pod wire-write order: the injected fault fires
                # only on a pod's FIRST bind attempt, so a bind-fault
                # arriving while the model already holds the pod placed
                # means a retry OVERTOOK its first attempt on the wire
                # — exactly the reordering the commit pipeline's
                # per-pod ordering keys exist to prevent.
                violations.append(Violation(
                    "commit-order", tick,
                    f"bind-fault for pod {uid} arrived after an "
                    f"accepted bind on {self._placed[uid]} — a retry "
                    "overtook its first attempt (per-pod wire-write "
                    "order broken)",
                ))
            if op == "bind":
                if uid in self._placed:
                    violations.append(Violation(
                        "double-bind", tick,
                        f"pod {uid} bound to {e['node']} while already "
                        f"placed on {self._placed[uid]} "
                        f"(prior status {e.get('prior_status')})",
                    ))
                self._placed[uid] = e["node"]
                if group is not None:
                    self._group_placed.setdefault(group, set()).add(uid)
            elif op == "evict":
                if e.get("prior_node") is None and \
                        uid not in self._placed:
                    violations.append(Violation(
                        "eviction-unaccounted", tick,
                        f"pod {uid} evicted while never placed "
                        f"(prior status {e.get('prior_status')})",
                    ))
                self._unplace(uid, group)
            elif op in ("unplace", "pod-gone"):
                self._unplace(uid, group)
        # Evicted pods must be observably unplaced by end of tick —
        # unless a LATER accepted bind re-placed them (a donor's drain
        # evictions legally re-pack onto its remaining nodes within
        # the same cycle).  Only an eviction with no subsequent bind
        # and a still-placed pod is a lost write.
        last_op: dict[str, str] = {}
        for e in entries:
            if e["op"] in ("bind", "evict"):
                last_op[e.get("uid")] = e["op"]
        for e in entries:
            if e["op"] != "evict" or \
                    last_op.get(e.get("uid")) == "bind":
                continue
            state = pods.get(e.get("uid"))
            if state is not None and state[2] is not None and \
                    state[1] not in (TaskStatus.PENDING,):
                violations.append(Violation(
                    "eviction-unaccounted", tick,
                    f"pod {e['uid']} evicted but still holds node "
                    f"{state[2]} in status {state[1].name}",
                ))
        for group in sorted(first_wave):
            need = min_member.get(group)
            if need is None:
                continue  # group completed within the same tick
            got = attempts.get(group, 0)
            if got < need:
                violations.append(Violation(
                    "gang-partial-bind", tick,
                    f"gang {group} got its first bind wave with only "
                    f"{got}/{need} member placements attempted — a "
                    "non-Ready gang leaked through the JobReady gate",
                ))
        return violations

    def _unplace(self, uid, group) -> None:
        self._placed.pop(uid, None)
        if group in self._group_placed:
            self._group_placed[group].discard(uid)

    # -- 3: capacity ----------------------------------------------------
    def _check_capacity(self, tick, pods, nodes):
        violations: list[Violation] = []
        used: dict[str, dict[str, float]] = {
            name: {} for name in nodes
        }
        for uid, (_group, status, node, request) in sorted(pods.items()):
            if node is None or status not in (
                TaskStatus.BOUND, TaskStatus.RUNNING,
            ):
                continue
            if node not in used:
                continue  # raced a vanish; the pods re-Pending next event
            for k, v in request.items():
                used[node][k] = used[node].get(k, 0.0) + float(v)
        for name, sums in sorted(used.items()):
            alloc = nodes[name]
            for k, v in sums.items():
                if v > float(alloc.get(k, 0.0)) + EPS:
                    violations.append(Violation(
                        "capacity-exceeded", tick,
                        f"node {name} over-committed on {k}: "
                        f"{v} used > {alloc.get(k, 0.0)} allocatable",
                    ))
        return violations

    # -- 5: convergence (engine calls at drain deadline) ----------------
    def pending_after_deadline(self, tick: int) -> list[Violation]:
        with self.cluster._lock:
            stuck = sorted(
                (p.group or "?", p.name)
                for p in self.cluster.pods.values()
                if p.status == TaskStatus.PENDING
            )
        if not stuck:
            return []
        groups = sorted({g for g, _n in stuck})
        return [Violation(
            "no-convergence", tick,
            f"{len(stuck)} pod(s) still Pending after the drain "
            f"deadline (gangs: {', '.join(groups[:8])}"
            f"{', ...' if len(groups) > 8 else ''})",
        )]
