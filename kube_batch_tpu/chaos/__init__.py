"""Chaos scenario engine: deterministic fault-injecting simulation.

Drives the REAL scheduler through its production wire stack
(StreamBackend/WatchAdapter against an instrumented ExternalCluster)
under seeded workload churn and injected failures, checking scheduling
invariants after every tick and dumping a flight recorder on failure.

    python -m kube_batch_tpu.chaos --seed 7 --ticks 200

See doc/design/chaos-engine.md for the event model, fault taxonomy,
invariants and the flight-recorder format.
"""

from kube_batch_tpu.chaos.engine import (
    ChaosEngine,
    ChaosEngineError,
    ChaosResult,
    FlightRecorder,
)
from kube_batch_tpu.chaos.faults import ChaosCluster, FaultSpec, plan_faults
from kube_batch_tpu.chaos.invariants import InvariantChecker, Violation
from kube_batch_tpu.chaos.workload import (
    ScenarioSpec,
    apply_to_cluster,
    apply_to_sim,
    generate,
    read_trace,
    trace_hash,
    write_trace,
)

__all__ = [
    "ChaosEngine",
    "ChaosEngineError",
    "ChaosResult",
    "ChaosCluster",
    "FaultSpec",
    "FlightRecorder",
    "InvariantChecker",
    "ScenarioSpec",
    "Violation",
    "apply_to_cluster",
    "apply_to_sim",
    "generate",
    "plan_faults",
    "read_trace",
    "trace_hash",
    "write_trace",
]
