"""Seeded arrival-process generation and the replayable JSONL trace.

The chaos engine's workload is a deterministic function of (spec, seed,
ticks): Poisson gang arrivals with periodic bursts, seeded gang sizes /
priorities / queues, planned node add/remove churn, and job completions
a seeded lifetime after submission.  The whole schedule is generated UP
FRONT as a flat list of event dicts — one JSON object per line in a
trace file — so a scenario is:

* **replayable**: a recorded ``.jsonl`` trace feeds the engine instead
  of a generator (``--scenario trace.jsonl``), and the same trace
  applies to either backend (`apply_to_cluster` drives the wire-side
  `ExternalCluster`, `apply_to_sim` the in-process simulator);
* **diffable**: events are canonical JSON (sorted keys, no whitespace),
  so two runs' traces diff line-by-line and hash stably
  (`trace_hash`).

Every object identity (pod/node/group uid) is assigned BY the
generator — the framework's process-global uid counter would otherwise
make a second run in the same process produce different uids and break
same-seed determinism.

Event grammar (all events carry ``tick`` and ``op``)::

    {"tick": -1, "op": "meta",       "seed": s, "bind_fail_pct": p,
     "slow_at": t, "slow_ticks": n, "slow_response_s": d,
     "blackhole_at": t, "blackhole_ticks": n, "hbm_pressure_at": t,
     "leader_crash_at": t, "zombie_writes": n,
     "flaky_at": t, "flaky_ticks": n, "flaky_fail_pct": p,
     "flaky_flap_every": n, "flaky_drain_budget": n}
    {"tick": 0, "op": "add-queue",   "name": q, "weight": w}
    {"tick": 0, "op": "add-node",    "node": {<codec NODE_KEYS dict>}}
    {"tick": t, "op": "remove-node", "name": n}
    {"tick": t, "op": "submit",      "group": g, "queue": q,
     "minMember": k, "priority": p, "pods": [{<codec POD_KEYS dict>}]}
    {"tick": t, "op": "complete",    "group": g, "uids": [...]}

``complete`` ticks may land past the scenario horizon — the engine
applies them during its convergence drain so outstanding demand keeps
freeing capacity.

The ``meta`` header (written first by the engine's ``--trace-out``)
makes a recorded trace self-describing: replay recovers the seed, the
bind-curse percentage, and the guardrail fault windows — all of which
shape RUN behavior (curse decisions, Guardrails wiring, wire
timeouts) rather than the inline event schedule, so they are not
derivable from the events — without the operator re-passing them.  It
is excluded from `trace_hash` so a recording and its replay hash
identically.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import random
from typing import Iterable

GI = float(1 << 30)


@dataclasses.dataclass(frozen=True)
class ScenarioSpec:
    """Knobs of the generated arrival process (all seeded)."""

    #: Base nodes present from tick 0 (never churned away).
    nodes: int = 6
    node_cpu_milli: float = 8000.0
    node_mem: float = 16 * GI
    node_pods: float = 110.0
    #: Poisson mean of gang arrivals per tick.
    arrival_rate: float = 0.5
    #: Every `burst_every` ticks, `burst_size` extra gangs land at once
    #: (0 disables) — the hostile-traffic spike the north star names.
    burst_every: int = 25
    burst_size: int = 3
    gang_min: int = 1
    gang_max: int = 5
    #: Fraction of a gang that must place before any member binds
    #: (min_member = ceil(frac * size); 1.0 = strict all-or-nothing).
    min_member_frac: float = 1.0
    #: Priority levels sampled uniformly per gang.
    priorities: tuple[int, ...] = (0, 10, 100)
    #: (name, weight) fair-share queues; gangs sample uniformly.
    queues: tuple[tuple[str, float], ...] = (
        ("default", 1.0), ("batch", 2.0),
    )
    #: Mean ticks a bound gang runs before completing (geometric).
    lifetime_mean: float = 30.0
    #: Every `node_churn_every` ticks an EXTRA node joins or the
    #: youngest extra leaves (alternating; 0 disables).  Base capacity
    #: is never churned, so admissible gangs stay admissible.
    node_churn_every: int = 40
    #: Arrivals pause while outstanding demand exceeds this fraction of
    #: BASE capacity — keeps every generated scenario convergent.
    target_utilization: float = 0.75


def _poisson(rng: random.Random, lam: float) -> int:
    """Knuth's method — fine for the small per-tick rates used here."""
    if lam <= 0.0:
        return 0
    limit = math.exp(-lam)
    k, p = 0, 1.0
    while True:
        p *= rng.random()
        if p <= limit:
            return k
        k += 1


def generate(
    spec: ScenarioSpec, seed: int, ticks: int
) -> list[dict]:
    """The full event schedule for one scenario — pure in (spec, seed,
    ticks), so the same seed always yields the identical trace."""
    rng = random.Random(f"chaos-workload-{seed}")
    events: list[dict] = []
    for name, weight in spec.queues:
        events.append({
            "tick": 0, "op": "add-queue", "name": name, "weight": weight,
        })
    for i in range(spec.nodes):
        events.append(_node_event(0, f"base-{i}", spec))

    queue_names = [q for q, _w in spec.queues]
    total_cpu = spec.nodes * spec.node_cpu_milli
    total_mem = spec.nodes * spec.node_mem
    outstanding_cpu = 0.0
    outstanding_mem = 0.0
    # (tick, group, uids, cpu, mem) completions keyed by fire tick.
    completions: list[tuple[int, str, list[str], float, float]] = []
    extra_nodes: list[str] = []
    gang_seq = 0
    extra_seq = 0

    for t in range(ticks):
        # -- planned node churn (extras only; base capacity is fixed) --
        if spec.node_churn_every and t and t % spec.node_churn_every == 0:
            if extra_nodes and rng.random() < 0.5:
                events.append({
                    "tick": t, "op": "remove-node",
                    "name": extra_nodes.pop(),
                })
            else:
                name = f"extra-{seed}-{extra_seq}"
                extra_seq += 1
                extra_nodes.append(name)
                events.append(_node_event(t, name, spec))

        # -- completions due this tick free their demand --------------
        for done in [c for c in completions if c[0] == t]:
            completions.remove(done)
            _dt, group, uids, cpu, mem = done
            outstanding_cpu -= cpu
            outstanding_mem -= mem
            events.append({
                "tick": t, "op": "complete", "group": group, "uids": uids,
            })

        # -- arrivals (Poisson + periodic burst), capacity-gated ------
        n = _poisson(rng, spec.arrival_rate)
        if spec.burst_every and t and t % spec.burst_every == 0:
            n += spec.burst_size
        for _ in range(n):
            size = rng.randint(spec.gang_min, spec.gang_max)
            cpu_per = float(rng.choice([250, 500, 1000, 2000]))
            mem_per = float(rng.choice([1, 2, 4])) * GI
            gang_cpu, gang_mem = size * cpu_per, size * mem_per
            if (
                outstanding_cpu + gang_cpu
                > spec.target_utilization * total_cpu
                or outstanding_mem + gang_mem
                > spec.target_utilization * total_mem
            ):
                continue  # backlogged: keep the scenario convergent
            group = f"gang-{seed}-{gang_seq}"
            gang_seq += 1
            queue = rng.choice(queue_names)
            priority = rng.choice(spec.priorities)
            min_member = max(1, math.ceil(spec.min_member_frac * size))
            pods = [
                {
                    "name": f"{group}-{i}",
                    "uid": f"uid-{group}-{i}",
                    "group": group,
                    "priority": priority,
                    "request": {
                        "cpu": cpu_per, "memory": mem_per, "pods": 1.0,
                    },
                }
                for i in range(size)
            ]
            events.append({
                "tick": t, "op": "submit", "group": group, "queue": queue,
                "minMember": min_member, "priority": priority, "pods": pods,
            })
            outstanding_cpu += gang_cpu
            outstanding_mem += gang_mem
            lifetime = max(1, int(rng.expovariate(1.0 / spec.lifetime_mean)))
            completions.append((
                t + 1 + lifetime, group,
                [p["uid"] for p in pods], gang_cpu, gang_mem,
            ))

    # Outstanding jobs complete past the horizon (the engine applies
    # these during its convergence drain so capacity keeps freeing).
    for when, group, uids, _cpu, _mem in sorted(completions):
        events.append({
            "tick": when, "op": "complete", "group": group, "uids": uids,
        })
    return events


def _node_event(tick: int, name: str, spec: ScenarioSpec) -> dict:
    return {
        "tick": tick, "op": "add-node",
        "node": {
            "uid": f"uid-node-{name}",
            "name": name,
            "allocatable": {
                "cpu": spec.node_cpu_milli,
                "memory": spec.node_mem,
                "pods": spec.node_pods,
            },
        },
    }


# -- trace format ------------------------------------------------------

def trace_lines(events: Iterable[dict]) -> list[str]:
    """Canonical JSONL: sorted keys, no whitespace — diffable and
    hash-stable across runs."""
    return [
        json.dumps(e, sort_keys=True, separators=(",", ":"))
        for e in events
    ]


def trace_hash(events: Iterable[dict]) -> str:
    h = hashlib.sha256()
    for line in trace_lines(events):
        h.update(line.encode())
        h.update(b"\n")
    return h.hexdigest()


def write_trace(path: str, events: Iterable[dict]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        for line in trace_lines(events):
            f.write(line + "\n")


def read_trace(path: str) -> list[dict]:
    events = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if line:
                events.append(json.loads(line))
    return events


# -- appliers (trace event → world mutation) ---------------------------

def _decode_submit(ev: dict):
    from kube_batch_tpu.cache.cluster import PodGroup
    from kube_batch_tpu.client.codec import decode_pod

    group = PodGroup(
        name=ev["group"],
        queue=ev.get("queue", ""),
        min_member=int(ev.get("minMember", 1)),
        priority=int(ev.get("priority", 0)),
        uid=f"uid-pg-{ev['group']}",
    )
    pods = [decode_pod(p) for p in ev["pods"]]
    return group, pods


def apply_to_cluster(cluster, ev: dict) -> None:
    """Apply one trace event to the authoritative wire-side cluster
    (`client.external.ExternalCluster`): the scheduler only ever learns
    about it through the watch stream."""
    from kube_batch_tpu.cache.cluster import Queue
    from kube_batch_tpu.client.codec import decode_node

    op = ev["op"]
    if op == "add-queue":
        cluster.add_queue(Queue(
            name=ev["name"], weight=float(ev.get("weight", 1.0)),
            cell=str(ev.get("cell", "")),
            uid=f"uid-queue-{ev['name']}",
        ))
    elif op == "add-node":
        cluster.add_node(decode_node(ev["node"]))
    elif op == "remove-node":
        cluster.delete_node(ev["name"])
    elif op == "submit":
        group, pods = _decode_submit(ev)
        cluster.submit(group, pods)
    elif op == "complete":
        cluster.complete_group(ev["group"])
    else:
        raise ValueError(f"unknown trace op {op!r}")


def apply_to_sim(sim, ev: dict) -> None:
    """Apply one trace event to the in-process simulator (the fast,
    thread-free backend) — same grammar, so a recorded chaos trace
    doubles as an offline workload for oracle/regression runs."""
    from kube_batch_tpu.cache.cluster import Queue
    from kube_batch_tpu.client.codec import decode_node

    op = ev["op"]
    if op == "add-queue":
        sim.add_queue(Queue(
            name=ev["name"], weight=float(ev.get("weight", 1.0)),
            uid=f"uid-queue-{ev['name']}",
        ))
    elif op == "add-node":
        sim.add_node(decode_node(ev["node"]))
    elif op == "remove-node":
        sim.delete_node(ev["name"])
    elif op == "submit":
        group, pods = _decode_submit(ev)
        sim.submit(group, pods)
    elif op == "complete":
        for uid in ev.get("uids", []):
            sim.delete_pod(uid)
        sim.delete_pod_group(ev["group"])
    else:
        raise ValueError(f"unknown trace op {op!r}")
