"""Deterministic fault injection over the wire-side cluster.

Two pieces:

* `plan_faults` — the fault SCHEDULE, a pure function of (spec, seed,
  ticks): which tick gets a stream drop, a watch gap (drop + expired
  history → the 410-Gone path), a node vanish (+ its later heal), or a
  lease steal (+ its return).  The plan is a list of trace-style event
  dicts, so it rides in the same JSONL trace as the workload and two
  runs of the same seed produce the identical schedule.

* `ChaosCluster` — `client.external.ExternalCluster` subclassed into a
  hostile, instrumented apiserver: it can curse a deterministic subset
  of pods so their FIRST bind attempt fails (the retry-through-resync
  path), and it records every bind/evict/unplacement as a structured
  wire-log entry (tick, uid, group, prior placement) that the invariant
  checker replays.  Failure decisions key on the pod's uid hash, never
  on call order — the scheduler's 16-way bind fan-out delivers requests
  in nondeterministic thread order, and a seeded-RNG-by-arrival rule
  would destroy same-seed reproducibility.

The stream-drop / gap / lease faults need the engine's cooperation
(it owns the socket and the lease renewal loop), so `plan_faults` only
schedules them; `engine.ChaosEngine` executes them.
"""

from __future__ import annotations

import collections
import dataclasses
import hashlib
import random

from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.client.external import ExternalCluster


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """Fault cadence knobs.  Every `*_every` is a tick period (0
    disables that fault class)."""

    #: Sever the wire; the engine reconnects and resumes the watch from
    #: its last-seen resourceVersion (the missed-tail replay path).
    stream_drop_every: int = 31
    #: Sever the wire AND expire the watch history, forcing the
    #: 410-Gone answer and the in-process clear()+re-list recovery.
    gap_every: int = 97
    #: Percentage of pods whose FIRST bind attempt gets an injected
    #: error response (decided by uid hash — deterministic under the
    #: bind fan-out's thread order); the resync retry must land.
    bind_fail_pct: int = 10
    #: Abruptly delete a live node (residents go back to Pending).
    node_vanish_every: int = 43
    #: Ticks until a vanished node's replacement (same capacity, same
    #: name) rejoins — keeps scenarios convergent.
    heal_after: int = 7
    #: A rogue holder usurps the cluster-side lease for one tick; the
    #: engine's renewal fails, it stands down, then re-acquires.
    lease_steal_every: int = 53

    # -- guardrail faults (kube_batch_tpu/guardrails/) -----------------
    # These are sustained WINDOWS (one onset tick + a duration), not
    # cadences: a breaker needs a dead backend long enough to trip and
    # probe, a watchdog needs consecutive overruns.  All default OFF —
    # they exist to exercise the self-protection ladder, and enabling
    # any of them makes the engine construct a Guardrails instance for
    # the driven scheduler (see engine.ChaosEngine).

    #: Tick the backend turns SLOW: write verbs (bind/evict/status/
    #: ping) are answered only after `slow_response_s` — every cycle
    #: that writes overruns, and the cycle watchdog must climb its
    #: degradation ladder.  0 disables; heals at slow_at + slow_ticks.
    slow_at: int = 0
    slow_ticks: int = 10
    slow_response_s: float = 0.4
    #: Tick the write path goes DARK: bind/evict/status/ping requests
    #: are swallowed with no response (the scheduler's calls time out;
    #: the watch and lease verbs stay live, so heal is observable).
    #: The wire breaker must trip open and quiesce scheduling.  0
    #: disables; heals at blackhole_at + blackhole_ticks.
    blackhole_at: int = 0
    blackhole_ticks: int = 8
    #: Tick the hbm-pressure fault fires: the engine compiles ONE
    #: next-bucket program through `Scheduler.warm_grown` under a
    #: 1-byte ceiling — HBM admission must refuse it and the previous
    #: program must keep serving.  0 disables.
    hbm_pressure_at: int = 0

    # -- node-health faults (kube_batch_tpu/health/) -------------------
    #: Tick one seeded node turns FLAKY: it stays on the wire and
    #: keeps answering, but a deterministic fraction of binds targeted
    #: at it are REFUSED (app-level answer — the transport lives, so
    #: the wire breaker must NOT trip) and its Ready condition flaps
    #: on a cadence, all below the vanish threshold.  The health
    #: ledger must quarantine the node, mask it out of placements,
    #: optionally drain its gangs, and re-admit it through probation
    #: after the heal at flaky_at + flaky_ticks.  0 disables.
    flaky_at: int = 0
    flaky_ticks: int = 12
    #: Percentage of bind attempts at the flaky node that get refused
    #: (hash of (seed, uid, attempt) — deterministic under the bind
    #: fan-out's thread order, and a retry can fail again: that is the
    #: point).
    flaky_fail_pct: int = 85
    #: NotReady condition flap cadence within the flaky window (the
    #: node recovers the following tick each time); 0 disables flaps.
    flaky_flap_every: int = 4
    #: Drain budget for the driven scheduler (gangs migrated per
    #: cycle); > 0 turns --drain-cordoned semantics on for the run so
    #: the gang-atomic-drain invariant is exercised.  0 = drain off.
    flaky_drain_budget: int = 0

    # -- crash-restart faults (kube_batch_tpu/statestore/) --------------
    #: Tick the scheduler PROCESS crash-restarts: the lease expires
    #: un-released, the in-memory world objects (ledger, guardrails,
    #: scheduler, commit pipeline) are thrown away, and the engine
    #: restarts as a fresh elector identity that wins a higher epoch,
    #: re-ADOPTS the durable statestore journal (quarantine, refusal
    #: pins, breaker/watchdog state), and runs the PR-4 takeover
    #: reconciliation — mid-quarantine / mid-refusal / mid-outage.
    #: 0 disables.
    crash_restart_at: int = 0
    #: How many crash-restarts (at crash_restart_at + k·every).
    crash_restarts: int = 1
    crash_restart_every: int = 8
    #: Tick a PERSISTENT HBM refusal pin is established: one
    #: next-bucket program compiles through warm_grown under a 1-byte
    #: ceiling (refused + pinned), then the ceiling settles between
    #: the serving and the refused projection so the pin stays VALID —
    #: the state a crash-restart must carry across (the engine probes
    #: after the last restart that the pin survived WITHOUT a
    #: recompile).  0 disables.
    hbm_pin_at: int = 0
    #: AOT compile-artifact bank dimension
    #: (doc/design/compile-artifacts.md): 0 = off; 1 = the driven
    #: scheduler banks every compile under the engine's state dir and
    #: mirrors it cluster-side (putCompileArtifact), and a
    #: crash-restart successor must ADOPT its predecessor's
    #: executables with zero inline compiles; 2 = same, but the LOCAL
    #: bank is wiped at each crash — simulating a successor on a
    #: different (matching-fingerprint) host that must adopt through
    #: the peer wire mirror alone.  The bank must be decision-
    #: invisible: `make chaos` pins same seed ⇒ same hash with the
    #: bank on and off.
    compile_bank: int = 0

    # -- device-loss faults (kube_batch_tpu/guardrails/mesh.py) ---------
    #: Tick the DEVICE-LOSS window opens: every sharded solve dispatch
    #: at a topology wider than `device_loss_devices` raises a
    #: DeviceLossError BEFORE any state mutates, so the mesh
    #: degradation ladder must classify, walk down to an admitted
    #: rung, and keep serving every cycle — then heal back up after
    #: the window (device_loss_at + device_loss_ticks) through the
    #: canary-solve streak.  0 disables.
    device_loss_at: int = 0
    device_loss_ticks: int = 10
    #: Devices that stay HEALTHY during the window — the widest
    #: topology a solve can dispatch at without the injected failure.
    #: The ladder must settle at this rung (or below, if a rung is
    #: HBM-refused) for the window's duration.
    device_loss_devices: int = 2
    #: Optional rung to FORCE-REFUSE: while the ladder holds this
    #: device count, its compile admission runs under a 1-byte HBM
    #: ceiling (the hbm-pressure fault's clamp model), so the rung
    #: must be skipped with MeshRungRefused instead of served.  0
    #: disables the refusal leg.
    device_loss_refuse_devices: int = 0

    # -- batched-ingest faults (doc/design/ingest-batching.md) ----------
    #: Tick the EVENT STORM opens: every tick of the window the
    #: cluster re-emits `storm_events` MODIFIED pod events (seeded
    #: round-robin over the SORTED live pod set — benign latest-wins
    #: churn carrying each pod's current truth), and one watch-gap
    #: fires mid-window so a relist must recover THROUGH the storm.
    #: The engine then asserts no event was lost (mirror parity vs
    #: the serially-authoritative cluster) and that ingest never
    #: starved the cycle thread past the watchdog ladder.  0 disables.
    storm_at: int = 0
    storm_ticks: int = 6
    storm_events: int = 60

    # -- failover faults (doc/design/failover-fencing.md) --------------
    #: Tick the LEADER CRASHES: its lease expires on the cluster
    #: without a release, pods it was mid-committing are left frozen
    #: in BINDING, and the engine restarts as a SECOND elector
    #: instance (fresh connection, fresh holder identity) that wins a
    #: strictly higher epoch and runs the takeover reconciliation —
    #: while the dead incarnation's connection stays OPEN and fires
    #: the zombie-flush window below.  0 disables.
    leader_crash_at: int = 0
    #: Size of the zombie-flush window: data-plane writes the DEAD
    #: incarnation attempts (through its still-open connection, with
    #: its stale epoch) AFTER the successor took over — deterministic
    #: stand-ins for the 16 flush workers that outlive a real crash's
    #: leadership.  Every one of them must be rejected StaleEpoch;
    #: one accepted zombie bind is a double-bind across leaders.
    zombie_writes: int = 2

    @classmethod
    def none(cls) -> "FaultSpec":
        return cls(stream_drop_every=0, gap_every=0, bind_fail_pct=0,
                   node_vanish_every=0, lease_steal_every=0)

    @property
    def guardrail_faults(self) -> bool:
        """Any guardrail fault configured — the engine then drives the
        scheduler with a Guardrails instance wired for tick time."""
        return bool(self.slow_at or self.blackhole_at
                    or self.hbm_pressure_at)

    @property
    def restart_faults(self) -> bool:
        """Crash-restart configured — the engine then journals the
        driven scheduler's operational state to a statestore and
        exercises warm-restart adoption (+ the survival invariants)."""
        return bool(self.crash_restart_at)

    @property
    def ingest_faults(self) -> bool:
        """The event-storm fault configured — the engine then wires a
        Guardrails instance so the never-starved-past-the-watchdog
        invariant is asserted against a LIVE ladder, and runs the
        mirror-parity (no-event-lost / latest-wins) check."""
        return bool(self.storm_at)

    @property
    def device_loss_faults(self) -> bool:
        """The device-loss fault configured — the engine then installs
        the solve-seam injector on the driven scheduler (and a
        Guardrails instance, so rung admission runs against a LIVE
        HBM ceiling) and asserts the mesh-ladder invariants."""
        return bool(self.device_loss_at)

    @property
    def health_faults(self) -> bool:
        """The flaky-node fault configured — the engine then drives
        the scheduler with a NodeHealthLedger (and a Guardrails
        instance, so the no-breaker-trip classification is actually
        asserted against a LIVE breaker)."""
        return bool(self.flaky_at)


def plan_faults(spec: FaultSpec, seed: int, ticks: int) -> list[dict]:
    """The full fault schedule, trace-event shaped.  Node-vanish events
    name no target — the victim is resolved at fire time from the live
    node set with the rng seeded here, which is equally deterministic
    and lets the plan survive workload-driven node churn."""
    del seed  # cadence is spec-driven; kept in the signature so a
    #           future jittered plan stays a same-shape change
    events: list[dict] = []
    for t in range(1, ticks):
        if spec.gap_every and t % spec.gap_every == 0:
            events.append({"tick": t, "op": "fault", "kind": "watch-gap"})
        elif spec.stream_drop_every and t % spec.stream_drop_every == 0:
            events.append({"tick": t, "op": "fault", "kind": "stream-drop"})
        if spec.node_vanish_every and t % spec.node_vanish_every == 0:
            events.append({"tick": t, "op": "fault", "kind": "node-vanish"})
            events.append({
                "tick": t + spec.heal_after, "op": "fault",
                "kind": "node-heal",
            })
        if spec.lease_steal_every and t % spec.lease_steal_every == 0:
            events.append({"tick": t, "op": "fault", "kind": "lease-steal"})
            events.append({
                "tick": t + 1, "op": "fault", "kind": "lease-return",
            })
    if spec.slow_at:
        events.append({
            "tick": spec.slow_at, "op": "fault", "kind": "slow-backend",
        })
        events.append({
            "tick": spec.slow_at + spec.slow_ticks, "op": "fault",
            "kind": "slow-heal",
        })
    if spec.blackhole_at:
        events.append({
            "tick": spec.blackhole_at, "op": "fault",
            "kind": "bind-blackhole",
        })
        events.append({
            "tick": spec.blackhole_at + spec.blackhole_ticks,
            "op": "fault", "kind": "blackhole-heal",
        })
    if spec.hbm_pressure_at:
        events.append({
            "tick": spec.hbm_pressure_at, "op": "fault",
            "kind": "hbm-pressure",
        })
    if spec.flaky_at:
        events.append({
            "tick": spec.flaky_at, "op": "fault", "kind": "flaky-node",
        })
        if spec.flaky_flap_every:
            # Ready-condition flaps within the window, each healing
            # the following tick — degradation, never a vanish.
            t = spec.flaky_at + spec.flaky_flap_every
            while t < spec.flaky_at + spec.flaky_ticks:
                events.append({
                    "tick": t, "op": "fault", "kind": "flaky-flap",
                })
                events.append({
                    "tick": t + 1, "op": "fault",
                    "kind": "flaky-flap-heal",
                })
                t += spec.flaky_flap_every
        events.append({
            "tick": spec.flaky_at + spec.flaky_ticks, "op": "fault",
            "kind": "flaky-heal",
        })
    if spec.device_loss_at:
        events.append({
            "tick": spec.device_loss_at, "op": "fault",
            "kind": "device-loss",
        })
        events.append({
            "tick": spec.device_loss_at + spec.device_loss_ticks,
            "op": "fault", "kind": "device-heal",
        })
    if spec.storm_at:
        for t in range(spec.storm_at, spec.storm_at + spec.storm_ticks):
            events.append({
                "tick": t, "op": "fault", "kind": "event-storm",
            })
        # One relist THROUGH the storm: the gap fires after the same
        # tick's storm burst (stable sort keeps plan order), so the
        # recovery replays a cluster still being churned.
        events.append({
            "tick": spec.storm_at + spec.storm_ticks // 2,
            "op": "fault", "kind": "watch-gap",
        })
    if spec.leader_crash_at:
        events.append({
            "tick": spec.leader_crash_at, "op": "fault",
            "kind": "leader-crash",
        })
    if spec.hbm_pin_at:
        events.append({
            "tick": spec.hbm_pin_at, "op": "fault", "kind": "hbm-pin",
        })
    if spec.crash_restart_at:
        last = spec.crash_restart_at
        for k in range(max(spec.crash_restarts, 1)):
            last = spec.crash_restart_at + k * max(
                spec.crash_restart_every, 1,
            )
            events.append({
                "tick": last, "op": "fault", "kind": "crash-restart",
            })
        if spec.hbm_pin_at:
            # Post-restart probe: the pin must answer from the RESTORED
            # state, without a recompile.  Offset past the last restart
            # so a restored-open breaker has quiesced, probed, healed
            # and run at least one REAL cycle first (the probe needs a
            # snapshot to grow from).
            events.append({
                "tick": last + 5, "op": "fault", "kind": "hbm-pin",
            })
    events.sort(key=lambda e: e["tick"])
    return events


def cursed(seed: int, uid: str, pct: int) -> bool:
    """True iff this pod's first bind attempt is fated to fail —
    a pure hash of (seed, uid), independent of delivery order."""
    if pct <= 0:
        return False
    digest = hashlib.sha256(f"chaos-bind-{seed}:{uid}".encode()).digest()
    return digest[0] % 100 < pct


def flaky_cursed(seed: int, uid: str, attempt: int, pct: int) -> bool:
    """True iff the flaky node refuses THIS bind attempt — a pure hash
    of (seed, uid, attempt number), so retries can fail again (the
    whole point of a flaky node) while staying independent of the
    bind fan-out's thread order."""
    if pct <= 0:
        return False
    digest = hashlib.sha256(
        f"chaos-flaky-{seed}:{uid}:{attempt}".encode()
    ).digest()
    return digest[0] % 100 < pct


class ChaosCluster(ExternalCluster):
    """ExternalCluster + deterministic bind sabotage + a structured
    wire log for the invariant checker.

    `tick_now` is stamped by the engine at the top of every tick; all
    mutation entry points run under the inherited cluster lock, so log
    appends are ordered and the checker drains them race-free.
    """

    #: Verbs the blackhole swallows and the slow fault delays — the
    #: scheduler's write path (the statestore's HA mirror included: a
    #: dead wire must not accept data-plane writes of any kind) plus
    #: the breaker's half-open probe.  The watch, LIST/resume and
    #: lease verbs stay live: a real "dead backend" outage keeps the
    #: informer side up (that is what makes heal observable), and the
    #: blackhole must not kill the engine's own per-tick lease
    #: renewal.
    WRITE_VERBS = frozenset({
        "bind", "evict", "updatePodGroup", "putStateSnapshot",
        "putCompileArtifact", "ping",
    })

    def __init__(self, *, seed: int = 0, bind_fail_pct: int = 0,
                 **kwargs) -> None:
        super().__init__(**kwargs)
        self.seed = seed
        self.bind_fail_pct = bind_fail_pct
        self.tick_now = 0
        self.wire_log: list[dict] = []
        self.bind_attempts: collections.Counter = collections.Counter()
        self.injected_bind_failures = 0
        self.recovered_binds = 0  # cursed pods whose retry later landed
        # -- guardrail fault state (engine-toggled) --------------------
        #: While True, WRITE_VERBS requests are swallowed: no response
        #: (the client times out), no mutation, no wire-log entry.
        #: Kept OUT of the wire log because how many attempts race in
        #: before the breaker trips depends on thread timing — hashing
        #: them would break same-seed reproducibility; the side
        #: counters below carry the evidence instead.
        self.blackhole = False
        #: Seconds each WRITE_VERBS response is held back while > 0
        #: (the slow-backend fault; responses still land, just late).
        self.response_delay = 0.0
        self.blackholed_requests = 0
        # -- flaky-node fault state (engine-toggled) -------------------
        #: While set, bind requests targeting this node are refused
        #: per flaky_cursed (an ANSWERED app-level failure — the wire
        #: lives, the NODE is sick; the breaker must not trip).
        self.flaky_node: str | None = None
        self.flaky_fail_pct = 0
        self.flaky_bind_failures = 0
        #: tick -> bind requests RECEIVED (answered or swallowed):
        #: the breaker-open invariant asserts this is zero for every
        #: tick the breaker spent fully open.
        self.bind_requests_by_tick: collections.Counter = \
            collections.Counter()
        #: tick -> ALL write-verb requests received EXCEPT the breaker's
        #: ping probe (bind/evict/updatePodGroup): the pipelined-commit
        #: dimension strengthens the breaker-open invariant from "zero
        #: binds" to "zero in-flight writes of any kind" — a status
        #: flush leaking through an open breaker is the same bug.
        self.write_requests_by_tick: collections.Counter = \
            collections.Counter()
        # The fencing epoch of the request CURRENTLY dispatching
        # (stashed under the cluster lock around super()._handle so
        # accepted bind/evict log entries carry the epoch that wrote
        # them — the single-writer-per-epoch invariant's evidence).
        self._req_epoch: int | None = None

    def _handle(self, writer, msg: dict) -> None:
        verb = msg.get("verb")
        is_write = verb in self.WRITE_VERBS or "path" in msg
        if verb == "bind":
            self.bind_requests_by_tick[self.tick_now] += 1
        if is_write and verb != "ping":
            self.write_requests_by_tick[self.tick_now] += 1
        if is_write and self.blackhole:
            self.blackholed_requests += 1
            return  # swallowed: caller times out, nothing mutates
        if is_write and self.response_delay > 0.0:
            import time

            time.sleep(self.response_delay)
        # RLock: reentrant with super()._handle's own acquisition —
        # the stash and the dispatch must be atomic against the 16-way
        # flush fan-out's concurrent requests.
        with self._lock:
            self._req_epoch = msg.get("epoch")
            try:
                super()._handle(writer, msg)
            finally:
                self._req_epoch = None

    # -- epoch instrumentation (ExternalCluster hooks) ------------------
    def _on_epoch_advance(self, epoch: int, holder: str,
                          cell: str = "") -> None:
        """Every mint rides the wire log (deterministic: acquires are
        engine-sequenced), so the invariant checker can replay which
        epoch was current when each write was accepted — per cell:
        each cell's lease mints its own sequence, and the checker
        keys its replay on the entry's cell ("" = the classic
        single-fleet lease, omitted so pre-cell hashes are stable)."""
        entry = {"op": "epoch-advance", "epoch": epoch,
                 "holder": holder}
        if cell:
            entry["cell"] = cell
        self._log(entry)

    def _on_stale_reject(self, msg: dict) -> None:
        """A zombie write was fenced.  Logged (the engine's zombie
        window fires deterministically, so these entries hash stably)
        and counted — the failover invariants assert the window was
        actually exercised."""
        self._log({
            "op": "stale-reject",
            "verb": msg.get("verb") or "k8s",
            "epoch": msg.get("epoch"),
        })

    # -- structured log -------------------------------------------------
    def _log(self, entry: dict) -> None:
        entry["tick"] = self.tick_now
        if self._req_epoch is not None and "epoch" not in entry:
            entry["epoch"] = self._req_epoch
        if self._req_cell is not None and "cell" not in entry:
            # Only cell-declaring writers stamp entries: classic
            # (uncelled) scenarios hash byte-identically to pre-cell
            # runs.
            entry["cell"] = self._req_cell
        self.wire_log.append(entry)

    # -- cell instrumentation (ExternalCluster hooks) -------------------
    def _on_cell_reject(self, why: str) -> None:
        """A cross-cell write was fenced cluster-side.  Logged (the
        cells engine's probes fire deterministically) and counted by
        the base class; the cells invariants assert ≥1 rejected and
        0 accepted."""
        self._log({"op": "cell-reject", "why": why})

    def _on_reclaim(self, entry: dict) -> None:
        """Reclaim negotiation steps (claim / grant / rollback) ride
        the wire log: they are engine-sequenced, so they hash stably,
        and the reclaim-atomic-or-rolled-back invariant replays
        them."""
        self._log(dict(entry))

    # -- bind sabotage + instrumentation -------------------------------
    def _bind_pod(self, writer, rid, pod, node_name) -> None:
        if pod is None:
            super()._bind_pod(writer, rid, pod, node_name)
            return
        self.bind_attempts[pod.uid] += 1
        first = self.bind_attempts[pod.uid] == 1
        if (
            self.flaky_node is not None
            and node_name == self.flaky_node
            and flaky_cursed(self.seed, pod.uid,
                             self.bind_attempts[pod.uid],
                             self.flaky_fail_pct)
        ):
            # The flaky kubelet refuses the bind but the apiserver
            # ANSWERED: app-level failure, per-node health evidence —
            # logged under its own op so the commit-order invariant
            # (which keys on first-attempt-only bind-faults) is not
            # confused by a refusal that may hit any attempt.
            self.flaky_bind_failures += 1
            self._log({
                "op": "flaky-bind-fault", "uid": pod.uid,
                "group": pod.group, "node": node_name,
            })
            self._respond(writer, rid, False,
                          "chaos: flaky kubelet refused bind")
            return
        if first and cursed(self.seed, pod.uid, self.bind_fail_pct):
            self.injected_bind_failures += 1
            self._log({
                "op": "bind-fault", "uid": pod.uid, "group": pod.group,
                "node": node_name,
            })
            self._respond(writer, rid, False,
                          "chaos: injected bind failure")
            return
        prior_status, prior_node = pod.status.name, pod.node
        accepted = (
            node_name in self.nodes
            and pod.name not in self.fail_bind_pods
            and self._cell_scope_violation(pod, node_name) is None
        )
        super()._bind_pod(writer, rid, pod, node_name)
        if accepted:
            if not first and cursed(self.seed, pod.uid,
                                    self.bind_fail_pct):
                self.recovered_binds += 1
            self._log({
                "op": "bind", "uid": pod.uid, "group": pod.group,
                "node": node_name, "prior_status": prior_status,
                "prior_node": prior_node,
            })

    def _evict_pod(self, writer, rid, pod, reason) -> None:
        if pod is not None and \
                self._cell_scope_violation(pod, None) is None:
            self._log({
                "op": "evict", "uid": pod.uid, "group": pod.group,
                "reason": reason, "prior_status": pod.status.name,
                "prior_node": pod.node,
            })
        super()._evict_pod(writer, rid, pod, reason)

    # -- unplacement bookkeeping (checker needs explicit transitions) --
    def delete_node(self, name: str) -> None:
        with self._lock:
            node = self.nodes.get(name)
            if node is not None:
                for pod in self.pods.values():
                    if pod.node == name:
                        self._log({
                            "op": "unplace", "uid": pod.uid,
                            "group": pod.group, "reason": "node-gone",
                        })
            super().delete_node(name)

    def delete_pod(self, uid: str) -> None:
        with self._lock:
            if uid in self.pods:
                self._log({"op": "pod-gone", "uid": uid,
                           "group": self.pods[uid].group})
            super().delete_pod(uid)

    # -- fault primitives the engine fires ------------------------------
    def vanish_node(self, rng: random.Random) -> dict | None:
        """Abruptly kill one live node (rng-chosen over the SORTED name
        set — deterministic), returning its FULL encoded spec for the
        later heal.  The full codec round trip matters: a node healing
        without its labels/taints/conditions would silently drop
        scheduling constraints (topology domains, toleration gates)
        the vanish never meant to remove."""
        from kube_batch_tpu.client.codec import encode_node

        with self._lock:
            names = sorted(self.nodes)
            if not names:
                return None
            name = rng.choice(names)
            spec = encode_node(self.nodes[name])
            self.delete_node(name)
            return spec

    def heal_node(self, spec: dict) -> None:
        """Restore a vanished node from its full encoded spec (same
        capacity, same name, same labels/taints/conditions/cordon
        state — codec parity with vanish_node)."""
        from kube_batch_tpu.client.codec import decode_node

        self.add_node(decode_node(spec))

    # -- event-storm primitive (engine-fired) ---------------------------
    def emit_storm(self, count: int) -> int:
        """Re-emit `count` MODIFIED events round-robin over the SORTED
        live pod set — each carries the pod's CURRENT truth, so the
        storm is pure ingest pressure (latest-wins coalescing fodder)
        with zero semantic state change; deterministic given the tick
        boundary's settled cluster state.  The events ride the history
        ring like any churn, so a mid-storm relist/resume replays
        them too.  Returns the number emitted."""
        from kube_batch_tpu.client.codec import encode_pod

        with self._lock:
            uids = sorted(self.pods)
            if not uids:
                return 0
            for i in range(count):
                pod = self.pods[uids[i % len(uids)]]
                self._emit("MODIFIED", "Pod", encode_pod(pod))
            return count

    # -- flaky-node primitives (engine-fired) ---------------------------
    def set_flaky(self, name: str | None, pct: int = 0) -> None:
        """Turn the flaky window on (name + refusal pct) or off
        (None).  The node stays fully on the wire either way."""
        with self._lock:
            self.flaky_node = name
            self.flaky_fail_pct = pct if name is not None else 0

    def flap_node(self, name: str, down: bool) -> None:
        """Flip the node's Ready condition (kubelet flap) — a
        MODIFIED event, never a DELETE: degradation below the vanish
        threshold, exactly what the health ledger scores as a flap."""
        from kube_batch_tpu.client.codec import encode_node

        with self._lock:
            node = self.nodes.get(name)
            if node is None:
                return
            node.ready = not down
            conds = dict(node.conditions)
            conds["Ready"] = not down
            node.conditions = conds
            self._emit("MODIFIED", "Node", encode_node(node))

    def steal_lease(self, usurper: str = "chaos-monkey") -> str | None:
        """A rogue holder takes the lease: the rightful holder's next
        renewal is rejected and it must stand down.  The steal MINTS
        an epoch — a new writer is a new epoch, so any in-flight
        write from the deposed holder is fenced from this instant."""
        import time

        with self._lock:
            previous = self.lease_holder
            self.lease_holder = usurper
            self.lease_expires = time.monotonic() + 3600.0
            self.lease_epoch += 1
            self.epoch_holders[self.lease_epoch] = usurper
            self._on_epoch_advance(self.lease_epoch, usurper)
            return previous

    def return_lease(self) -> None:
        with self._lock:
            self.lease_holder = None
            self.lease_expires = 0.0

    # -- deliberate corruption (invariant-checker self-test) ------------
    def force_double_bind(self) -> bool:
        """Corrupt the world the way a buggy scheduler would: bind an
        ALREADY-PLACED pod a second time, to a different node, behind
        the normal funnel's back.  Returns True when a target existed —
        the invariant checker MUST flag the resulting log entry."""
        with self._lock:
            placed = sorted(
                (uid, p) for uid, p in self.pods.items()
                if p.node is not None
                and p.status in (TaskStatus.BOUND, TaskStatus.RUNNING)
            )
            if not placed or len(self.nodes) < 2:
                return False
            uid, pod = placed[0]
            other = next(
                (n for n in sorted(self.nodes) if n != pod.node), None
            )
            if other is None:
                return False
            self._log({
                "op": "bind", "uid": uid, "group": pod.group,
                "node": other, "prior_status": pod.status.name,
                "prior_node": pod.node,
            })
            pod.node = other
            self.binds.append((pod.name, other))
            return True
