"""Prometheus-style metrics: the observability surface of the scheduler.

Reference counterpart: pkg/scheduler/metrics/metrics.go — subsystem
`kube_batch` histograms/counters (e2e scheduling latency, per-action and
per-plugin latency, schedule attempts by result, preemption attempts and
victims), registered with the Prometheus client and served on
`--listen-address`.

Dependency-free reimplementation: the same metric names and types, a
process-global registry, text exposition in the Prometheus format, and
an optional stdlib HTTP listener.  Device-side timing note: jitted
solves are asynchronous — timers that should include device work must
block on the result (`jax.block_until_ready`), which the scheduler loop
does once per cycle anyway when decoding placements.
"""

from __future__ import annotations

import http.server
import threading
import time
from typing import Iterable

SUBSYSTEM = "kube_batch"

# Reference bucket layout: prometheus.DefBuckets-ish, in seconds.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0
)


class _Metric:
    def __init__(self, name: str, help_: str, labels: tuple[str, ...] = ()):
        self.name = f"{SUBSYSTEM}_{name}"
        self.help = help_
        self.label_names = labels
        self._lock = threading.Lock()

    @staticmethod
    def _label_str(values: tuple[str, ...], names: tuple[str, ...]) -> str:
        if not names:
            return ""
        pairs = ",".join(f'{k}="{v}"' for k, v in zip(names, values))
        return "{" + pairs + "}"


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_, labels=()):
        super().__init__(name, help_, labels)
        self._values: dict[tuple[str, ...], float] = {}

    def inc(self, *labels: str, by: float = 1.0) -> None:
        with self._lock:
            self._values[labels] = self._values.get(labels, 0.0) + by

    def value(self, *labels: str) -> float:
        with self._lock:
            return self._values.get(labels, 0.0)

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.kind}"
        with self._lock:
            for labels, v in sorted(self._values.items()):
                yield f"{self.name}{self._label_str(labels, self.label_names)} {v}"


class Gauge(Counter):
    kind = "gauge"

    def set(self, value: float, *labels: str) -> None:
        with self._lock:
            self._values[labels] = value


class Histogram(_Metric):
    kind = "histogram"

    def __init__(self, name, help_, labels=(), buckets=DEFAULT_BUCKETS):
        super().__init__(name, help_, labels)
        self.buckets = tuple(buckets)
        self._counts: dict[tuple[str, ...], list[int]] = {}
        self._sums: dict[tuple[str, ...], float] = {}

    def observe(self, value: float, *labels: str) -> None:
        with self._lock:
            counts = self._counts.setdefault(labels, [0] * (len(self.buckets) + 1))
            self._sums[labels] = self._sums.get(labels, 0.0) + value
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            counts[-1] += 1  # +Inf

    def time(self, *labels: str):
        """Context manager: observe the wall time of a block."""
        hist = self

        class _Timer:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                hist.observe(time.perf_counter() - self.t0, *labels)
                return False

        return _Timer()

    def count(self, *labels: str) -> int:
        with self._lock:
            c = self._counts.get(labels)
            return c[-1] if c else 0

    def sum(self, *labels: str) -> float:
        with self._lock:
            return self._sums.get(labels, 0.0)

    def quantile(self, q: float, *labels: str) -> float:
        """Approximate quantile from bucket boundaries (upper bound of
        the bucket containing the q-th observation)."""
        with self._lock:
            c = self._counts.get(labels)
            if not c or c[-1] == 0:
                return 0.0
            target = q * c[-1]
            for i, b in enumerate(self.buckets):
                if c[i] >= target:
                    return b
            return float("inf")

    def expose(self) -> Iterable[str]:
        yield f"# HELP {self.name} {self.help}"
        yield f"# TYPE {self.name} {self.kind}"
        with self._lock:
            for labels, counts in sorted(self._counts.items()):
                base = self._label_str(labels, self.label_names)
                for i, b in enumerate(self.buckets):
                    le = self._label_str(
                        labels + (str(b),), self.label_names + ("le",)
                    )
                    yield f"{self.name}_bucket{le} {counts[i]}"
                inf = self._label_str(
                    labels + ("+Inf",), self.label_names + ("le",)
                )
                yield f"{self.name}_bucket{inf} {counts[-1]}"
                yield f"{self.name}_sum{base} {self._sums[labels]}"
                yield f"{self.name}_count{base} {counts[-1]}"


class Registry:
    def __init__(self) -> None:
        self._metrics: list[_Metric] = []
        self._lock = threading.Lock()

    def register(self, metric: _Metric) -> _Metric:
        with self._lock:
            self._metrics.append(metric)
        return metric

    def expose(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics)
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


REGISTRY = Registry()

# -- the reference's metric set (metrics.go) --------------------------------
e2e_latency = REGISTRY.register(Histogram(
    "e2e_scheduling_latency_seconds",
    "End-to-end scheduling cycle latency (snapshot to commit).",
))
action_latency = REGISTRY.register(Histogram(
    "action_scheduling_latency_seconds",
    "Per-action execution latency.",
    labels=("action",),
))
plugin_latency = REGISTRY.register(Histogram(
    "plugin_scheduling_latency_seconds",
    "Per-plugin session-hook latency.",
    labels=("plugin", "hook"),
))
schedule_attempts = REGISTRY.register(Counter(
    "schedule_attempts_total",
    "Scheduling cycles by result (scheduled|unschedulable|error).",
    labels=("result",),
))
pods_bound = REGISTRY.register(Counter(
    "pod_bind_total", "Pods bound to nodes.",
))
pods_evicted = REGISTRY.register(Counter(
    "pod_evict_total", "Pods evicted, by action (preempted|reclaimed).",
    labels=("reason",),
))
preemption_attempts = REGISTRY.register(Counter(
    "preemption_attempts_total",
    "Preempt/reclaim sweeps that chose at least one victim "
    "(metrics.go counts real attempts, not action executions).",
))
preemption_victims = REGISTRY.register(Counter(
    "preemption_victims_total",
    "Victim tasks transitioned to Releasing by preempt/reclaim.",
))
task_scheduling_latency = REGISTRY.register(Histogram(
    "task_scheduling_latency_seconds",
    "Per-task latency from Pending arrival in the cache to its "
    "successful bind dispatch (≙ metrics.go · TaskSchedulingLatency).",
))
snapshot_pack_latency = REGISTRY.register(Histogram(
    "snapshot_pack_latency_seconds",
    "HostSnapshot to device-tensor packing latency (H2D boundary).",
))
pack_h2d_bytes = REGISTRY.register(Counter(
    "pack_h2d_bytes_total",
    "Host-to-device bytes shipped by the tensor packers: full-pack "
    "pytree uploads, whole changed arrays, and row-patch payloads "
    "(indices + dirty rows).  A steady cycle on the row-patch path "
    "moves a few KB here; a sustained whole-array-sized rate signals "
    "a pack regression (doc/design/daemon-operations.md).",
))
pack_total = REGISTRY.register(Counter(
    "pack_total",
    "Tensor packs by mode: full (rebuild, incl. fallbacks — see the "
    "packer's fallback_reasons), row_patch (at least one changed "
    "field shipped as dirty rows through the scatter kernel), "
    "incremental (patched host arrays, but every changed field "
    "re-uploaded whole — e.g. a churn burst past the dirty-fraction "
    "threshold).",
    labels=("mode",),
))
pending_tasks = REGISTRY.register(Gauge(
    "pending_tasks", "Tasks still pending at session close.",
))
idle_cycles_skipped = REGISTRY.register(Counter(
    "idle_cycles_skipped_total",
    "Cycles that skipped the solve dispatch entirely: no pending or "
    "releasing pods, no failed-bind resync, no policy change.",
))
chaos_faults_injected = REGISTRY.register(Counter(
    "chaos_faults_injected_total",
    "Faults the chaos engine injected, by kind (stream-drop|watch-gap|"
    "bind-fault|node-vanish|lease-steal).",
    labels=("kind",),
))
chaos_recoveries = REGISTRY.register(Counter(
    "chaos_recoveries_total",
    "Observed recoveries from injected faults, by kind (resumed|"
    "relisted|bind-retried|node-healed|lease-reacquired).",
    labels=("kind",),
))
chaos_invariant_violations = REGISTRY.register(Counter(
    "chaos_invariant_violations_total",
    "Invariant violations the chaos checker flagged, by kind.",
    labels=("kind",),
))
chaos_convergence_ticks = REGISTRY.register(Gauge(
    "chaos_convergence_ticks",
    "Ticks from scenario quiescence until every admissible gang was "
    "bound in the last chaos run (-1 while unconverged).",
))
cycle_phase_latency = REGISTRY.register(Histogram(
    "cycle_phase_latency_seconds",
    "Within-cycle phase attribution (VERDICT r4 #4): dispatch = "
    "enqueueing the fused solve; solve_d2h = device compute wait + the "
    "batched D2H read; evict_commit = landing victim evictions; "
    "bind_dispatch = gang-gated bind fan-out (with the pipelined wire "
    "commit this is ENQUEUE time — wire RTTs land in "
    "commit_flush_latency_seconds); diagnosis = why-unschedulable "
    "tallies; status_writeback = PodGroup status recompute + writes; "
    "pack_host_patch = host-side array build/patch inside the pack; "
    "pack_h2d = the pack's device upload (whole arrays + row patches). "
    "Total pack time is snapshot_pack_latency.",
    labels=("phase",),
))

# -- batched watch ingestion (client/adapter.py; doc/design/ingest-batching.md)
ingest_events = REGISTRY.register(Counter(
    "ingest_events_total",
    "Watch events received by the batched ingest pipeline, by object "
    "kind (counts every event as it arrives, including ones later "
    "coalesced away).  The per-event differential baseline "
    "(--ingest-mode event) deliberately does not feed these — it is "
    "the unchanged legacy path.",
    labels=("kind",),
))
ingest_batch_size = REGISTRY.register(Histogram(
    "ingest_batch_size",
    "Events per coalesced ingest batch (one cache-lock acquisition "
    "each).  A steady stream of size-1 batches means the applier is "
    "keeping up per event; large batches mean bursts are being "
    "absorbed without per-event lock traffic.",
    buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1024, 4096, 16384,
             65536),
))
ingest_coalesced = REGISTRY.register(Counter(
    "ingest_coalesced_total",
    "Watch events absorbed by per-object latest-wins coalescing "
    "before any JSON/object decode or cache apply (N MODIFIEDs of one "
    "pod in a batch -> one apply; ADDED+DELETED annihilate).",
))
ingest_apply_latency = REGISTRY.register(Histogram(
    "ingest_apply_latency_seconds",
    "Wall time of one batched cache apply (the single lock hold that "
    "lands a whole ingest batch, including the relist sweep).",
))
ingest_lag = REGISTRY.register(Histogram(
    "ingest_lag_seconds",
    "Age of the NEWEST event in a batch at the moment its apply "
    "lands — the freshness of the mirror behind the wire.  A growing "
    "lag means ingest is falling behind the event rate "
    "(doc/design/daemon-operations.md · ingest-lag runbook).",
))

# -- pipelined wire commit (framework/commit.py) -----------------------------
commit_queue_depth = REGISTRY.register(Gauge(
    "commit_queue_depth",
    "Flush ops queued+running in the asynchronous commit pipeline "
    "(bounded by --commit-inflight-max; submissions past the bound "
    "pause the solve).",
))
commit_flush_latency = REGISTRY.register(Histogram(
    "commit_flush_latency_seconds",
    "Per-op latency from commit enqueue to wire ack (bind / status / "
    "event flushes through the commit pipeline).",
    labels=("verb",),
))
cycle_overlap_ratio = REGISTRY.register(Gauge(
    "cycle_overlap_ratio",
    "Fraction of commit-flush busy time that overlapped in-cycle "
    "compute (cycle N's wire RTTs hidden behind cycle N+1's pack + "
    "solve); 0 = fully serialized, 1 = fully hidden.",
))
commit_backpressure_waits = REGISTRY.register(Counter(
    "commit_backpressure_waits_total",
    "Commit submissions that blocked on the --commit-inflight-max "
    "bound (the solve paused instead of the queue growing).",
))
commit_flush_errors = REGISTRY.register(Counter(
    "commit_flush_errors_total",
    "Flush ops that raised past the cache's own failure funnels "
    "(bugs; the worker survives and logs the stack).",
))

# -- SLO burn-rate engine (kube_batch_tpu/trace/slo.py) ----------------------
slo_burn_rate = REGISTRY.register(Gauge(
    "slo_burn_rate",
    "Error-budget burn rate per SLO objective and evaluation window "
    "(burn = bad_fraction / error_budget; 1.0 = spending the budget "
    "exactly on schedule).  Fast-burn alerts fire when BOTH fast "
    "windows exceed their threshold (default 14.4x over 5m AND 1h) "
    "and auto-dump a flight-recorder post-mortem "
    "(doc/design/observability.md).",
    labels=("slo", "window"),
))
slo_breaches = REGISTRY.register(Counter(
    "slo_breaches_total",
    "Fresh fast-burn SLO breaches per objective (a sustained burn "
    "counts once until it clears and re-fires).",
    labels=("slo",),
))

# -- guardrail subsystem (kube_batch_tpu/guardrails/) ------------------------
guardrail_state = REGISTRY.register(Gauge(
    "guardrail_state",
    "Degradation-ladder rung of the cycle watchdog "
    "(0 ok, 1 degraded, 2 overloaded); mirrored by /healthz.",
))
# Exposed from process start (not from a constructor: Guardrails /
# CycleWatchdog instances must never reset the process-global rung a
# LIVE instance already published — see test_second_scheduler_does_
# not_stomp_health).
guardrail_state.set(0.0)
cycle_overrun_total = REGISTRY.register(Counter(
    "cycle_overrun_total",
    "Scheduling cycles whose wall latency exceeded the schedule "
    "period (the watchdog's escalation signal).",
))
breaker_state = REGISTRY.register(Gauge(
    "breaker_state",
    "Wire circuit-breaker state per backend "
    "(0 closed, 1 half-open, 2 open).",
    labels=("backend",),
))
wire_backoff_retries = REGISTRY.register(Counter(
    "wire_backoff_retries_total",
    "Transient-error retries of backend write verbs under the "
    "bounded-exponential-backoff policy.",
    labels=("verb",),
))
hbm_projected_bytes = REGISTRY.register(Gauge(
    "hbm_projected_bytes",
    "Last XLA memory_analysis projection of a candidate executable's "
    "device memory (growth-prewarm admission input).",
))
hbm_admission_refusals = REGISTRY.register(Counter(
    "hbm_admission_refusals_total",
    "Candidate programs the HBM-ceiling admission refused to adopt.",
))
hbm_blocked_cycles = REGISTRY.register(Counter(
    "hbm_blocked_cycles_total",
    "Cycles whose solve was PAUSED because the snapshot's shapes "
    "require a program the HBM-ceiling admission refused (placed "
    "work keeps running; pending rows wait for shrink or operator "
    "action).",
))

# -- mesh degradation ladder (kube_batch_tpu/guardrails/mesh.py) -------------
mesh_rung = REGISTRY.register(Gauge(
    "mesh_rung",
    "Device-loss degradation-ladder rung of the sharded solve "
    "(0 = full configured mesh; each rung halves the device count "
    "down to the single-device floor); mirrored by the /healthz "
    "`mesh` entry.",
))
# Exposed from process start (not from a constructor: MeshLadder
# instances must never reset the process-global rung a LIVE instance
# already published — same discipline as guardrail_state above).
mesh_rung.set(0.0)
mesh_rung_shifts = REGISTRY.register(Counter(
    "mesh_rung_shifts_total",
    "Mesh-ladder rung transitions by direction ('down' = device-loss "
    "degradation or HBM-refused-rung skip, 'up' = canary-streak "
    "heal).",
    labels=("direction",),
))
mesh_solve_failures = REGISTRY.register(Counter(
    "mesh_solve_failures_total",
    "Sharded-solve failures at the run_once seam by classification "
    "('device' walks the degradation ladder; 'data' re-raises — a "
    "program bug fails identically at every topology).",
    labels=("class",),
))

# -- AOT compile-artifact bank + no-block compile ladder ---------------------
# (kube_batch_tpu/compile_cache.py · ArtifactBank; scheduler.py ·
#  _ensure_compiled; doc/design/compile-artifacts.md)
compile_artifacts_banked = REGISTRY.register(Counter(
    "compile_artifacts_banked_total",
    "Compiled fused-cycle executables serialized into the AOT "
    "artifact bank (inline compiles, growth prewarms, conf prewarms "
    "and the warm tool all export here).",
))
compile_artifacts_adopted = REGISTRY.register(Counter(
    "compile_artifacts_adopted_total",
    "Cycles that ADOPTED a banked executable instead of compiling — "
    "a warm failover/restart records these where a cold one records "
    "compile_inline_total.",
))
compile_artifact_rejected = REGISTRY.register(Counter(
    "compile_artifact_rejected_total",
    "Bank entries refused at load, by reason (truncated, crc, "
    "header, version, host, key, deserialize, io): every refusal "
    "degrades to 'compile fresh' — never a crash, never a foreign "
    "executable loaded.",
    labels=("reason",),
))
compile_artifact_peer_adopted = REGISTRY.register(Counter(
    "compile_artifact_peer_adopted_total",
    "Artifact entries merged into the local bank from a peer's wire "
    "mirror at startup/takeover (matching host fingerprint only).",
))
compile_inline_total = REGISTRY.register(Counter(
    "compile_inline_total",
    "Fused-cycle compiles paid ON the cycle thread (the compile "
    "cliff this subsystem exists to remove; a warm bank + prewarm "
    "keeps this at the cold-start minimum).",
))
compile_background_total = REGISTRY.register(Counter(
    "compile_background_total",
    "Fused-cycle compiles run on a background thread (growth "
    "prewarm, conf prewarm, and no-block deferrals).",
))
compile_pending_cycles = REGISTRY.register(Counter(
    "compile_pending_cycles_total",
    "Cycles served DEGRADED by the no-block compile ladder: the "
    "needed bucket's executable was still compiling in the "
    "background, so the cycle kept serving the last compiled bucket "
    "with overflow rows held Pending (CompilePending event).",
))
compile_inflight = REGISTRY.register(Gauge(
    "compile_inflight",
    "Background fused-cycle compiles currently in flight (growth "
    "prewarm worker + no-block deferrals); mirrored by /healthz.",
))
compile_inflight.set(0.0)
warm_queue_depth = REGISTRY.register(Gauge(
    "warm_queue_depth",
    "Pending growth-prewarm shape variants queued behind the "
    "background compile worker; mirrored by /healthz.",
))
warm_queue_depth.set(0.0)

# -- node-health subsystem (kube_batch_tpu/health/) --------------------------
node_health_state = REGISTRY.register(Gauge(
    "node_health_state",
    "Health-ledger state per node (0 ok, 1 suspect, 2 cordoned, "
    "3 probation); transitions also emit Node events.",
    labels=("node",),
))
quarantined_nodes = REGISTRY.register(Gauge(
    "quarantined_nodes",
    "Nodes currently CORDONED by the health ledger (masked out of new "
    "placements; running pods stay) — mirrored by the /healthz body's "
    "`quarantined` count.",
))
quarantined_nodes.set(0.0)
drain_evictions = REGISTRY.register(Counter(
    "drain_evictions_total",
    "Pods evicted by the gang-atomic --drain-cordoned migration "
    "(each one had a proven re-placement on healthy capacity).",
))
probation_failures = REGISTRY.register(Counter(
    "probation_failures_total",
    "Probation nodes re-cordoned by a failure during their canary "
    "window (the quarantine threshold escalates each time).",
))

# -- durable operational memory (kube_batch_tpu/statestore/) -----------------
statestore_records = REGISTRY.register(Gauge(
    "statestore_records",
    "Records currently in the operational-state journal (appends since "
    "the last compaction, plus the header and compacted snapshot); a "
    "monotonically growing value here means compaction stopped firing.",
))
statestore_compactions = REGISTRY.register(Counter(
    "statestore_compactions_total",
    "Operational-state journal compactions (the file is rewritten down "
    "to the latest snapshot, fsynced, and — in HA mode — mirrored "
    "through the wire dialect for successor adoption).",
))
statestore_load_corrupt = REGISTRY.register(Counter(
    "statestore_load_corrupt_total",
    "Journal records dropped at load because their CRC frame, JSON "
    "body, or header failed to validate (the loader recovers the "
    "longest valid prefix and never raises).",
))
statestore_load_dropped_stale = REGISTRY.register(Counter(
    "statestore_load_dropped_stale_total",
    "Persisted node-health records dropped at load by the "
    "--state-max-age-cycles staleness decay (older evidence decays "
    "toward ok instead of quarantining on ancient history).",
))
state_adopted = REGISTRY.register(Counter(
    "state_adopted_total",
    "Operational-state adoptions at startup/takeover by source: "
    "'journal' (this host's --state-dir) or 'peer' (the dead leader's "
    "mirrored snapshot read back through the wire dialect).",
    labels=("source",),
))

# -- leadership fencing + failover (doc/design/failover-fencing.md) ----------
leader_epoch = REGISTRY.register(Gauge(
    "leader_epoch",
    "Fencing epoch of this process's current leadership (0 = standby "
    "or no leader election wired); bumps monotonically on every "
    "change of hands, mirrored by /healthz.",
))
# Exposed from process start, same rationale as guardrail_state: a
# second elector/Scheduler constructed in-process must never erase a
# live daemon's published epoch — transitions publish via
# set_leadership only.
leader_epoch.set(0.0)
cross_cell_writes = REGISTRY.register(Counter(
    "cross_cell_writes_total",
    "Data-plane writes rejected by cell-scope fencing (cluster-side "
    "CellScope answers plus locally-fenced fast-fails): each one is a "
    "write that targeted an object OUTSIDE the writer's cell and was "
    "PREVENTED from mutating it (doc/design/multi-cell.md).",
))
reclaim_claims = REGISTRY.register(Counter(
    "reclaim_claims_total",
    "Cross-cell capacity claims resolved, by outcome: 'granted' "
    "(every requested node moved), 'rolled_back' (TTL fired with "
    "nothing moved — the donor was dark or refused), 'expired' "
    "(multi-node claim closed FRACTIONALLY at TTL: the filled nodes "
    "stay, the remainder rolled back).  Counted at the CLAIMANT on "
    "resolution, whether the claim was typed by an operator or "
    "issued by the autopilot (doc/design/fleet-autopilot.md).",
    labels=("outcome",),
))
stale_epoch_writes = REGISTRY.register(Counter(
    "stale_epoch_writes_total",
    "Data-plane writes rejected by epoch fencing (cluster-side "
    "StaleEpoch answers plus locally-fenced fast-fails): each one is "
    "a zombie write from a deposed leadership epoch that was "
    "PREVENTED from mutating the cluster.",
))
failover_recovery = REGISTRY.register(Histogram(
    "failover_recovery_seconds",
    "Takeover reconciliation latency: new leadership epoch acquired "
    "-> relisted world reconciled (BINDING pods classified, PodGroup "
    "statuses repaired) and scheduling eligible to resume.",
))

# -- /healthz state (set by the guardrail watchdog + the elector) ------------
_health_lock = threading.Lock()
_health_state = "ok"
_health_role = "standby"
_health_epoch = 0
_health_quarantined = 0
_health_ingest_lag = 0.0
_health_cell = ""
_health_cell_peer_visible: bool | None = None
_health_mesh_devices = 1
#: Pending-demand column + autopilot ladder state (doc/design/
#: fleet-autopilot.md) — None until first published, and then only
#: surfaced: bodies of daemons that never compute them are unchanged.
_health_demand: dict | None = None
_health_autopilot: dict | None = None
#: Mesh degradation-ladder state (guardrails/mesh.py) — None until a
#: mesh-enabled scheduler publishes; single-device daemons serve an
#: unchanged body.
_health_mesh: dict | None = None
#: Per-SCOPE health registry (multi-scheduler-per-process): a live
#: scheduler driven under a bound scope (kube_batch_tpu/scope.py —
#: the cell name) publishes here instead of stomping the process-
#: global fields above; /healthz surfaces the whole registry under
#: "cells".  Empty in single-scheduler processes — zero change.
_health_scopes: dict[str, dict] = {}


def _resolve_scope(scope) -> str | None:
    """Explicit scope argument wins; else the calling thread's bound
    scope (kube_batch_tpu/scope.py); else None = process-global.
    "" normalizes to None either way — a thread explicitly bound to
    the EMPTY scope (single-scheduler daemon worker threads) must
    publish to the process-global fields, never a phantom "" entry."""
    if scope is not None:
        return scope or None
    from kube_batch_tpu import scope as scope_mod

    return scope_mod.current() or None


def _scope_entry(name: str) -> dict:
    return _health_scopes.setdefault(name, {
        "state": "ok", "role": "standby", "epoch": 0,
        "quarantined": 0, "cell_peer_visible": None,
        # Backlog pressure PER SCOPE: two in-process schedulers (the
        # cells chaos drive, bench cells_aggregate) must not report
        # each other's ingest lag / commit depth through the
        # process-global gauges.
        "ingest_lag_seconds": 0.0, "commit_queue_depth": 0,
    })


def set_health_state(state: str, scope: str | None = None) -> None:
    """Transition the /healthz body's `state` (ok | degraded |
    overloaded) — the watchdog's rung, externally observable without
    scraping metrics (load-balancers and runbooks read this).  Under
    a bound scope (a cell's scheduler) the transition lands in that
    scope's registry entry instead of the process-global field."""
    global _health_state
    s = _resolve_scope(scope)
    with _health_lock:
        if s is not None:
            _scope_entry(s)["state"] = state
        else:
            _health_state = state


def health_state(scope: str | None = None) -> str:
    s = _resolve_scope(scope)
    with _health_lock:
        if s is not None:
            # Non-creating read: probing an unknown scope must not
            # materialize a phantom /healthz "cells" entry.
            entry = _health_scopes.get(s)
            return entry["state"] if entry else "ok"
        return _health_state


def set_leadership(role: str, epoch: int,
                   scope: str | None = None) -> None:
    """Publish this process's election role ("leader" | "standby")
    and fencing epoch to /healthz and the `leader_epoch` gauge — the
    runbook's first question after a failover is "who leads, and at
    what epoch" (doc/design/failover-fencing.md)."""
    global _health_role, _health_epoch
    s = _resolve_scope(scope)
    with _health_lock:
        if s is not None:
            entry = _scope_entry(s)
            entry["role"] = role
            entry["epoch"] = int(epoch)
        else:
            _health_role = role
            _health_epoch = int(epoch)
    if s is None:
        leader_epoch.set(float(epoch))


def leadership(scope: str | None = None) -> tuple[str, int]:
    s = _resolve_scope(scope)
    with _health_lock:
        if s is not None:
            entry = _health_scopes.get(s)  # non-creating, like health_state
            return (entry["role"], entry["epoch"]) if entry \
                else ("standby", 0)
        return _health_role, _health_epoch


def set_quarantined(count: int, scope: str | None = None) -> None:
    """Publish the health ledger's cordoned-node count to /healthz —
    a fleet runbook's "is degraded hardware masked right now" read,
    without scraping /metrics (doc/design/node-health.md)."""
    global _health_quarantined
    s = _resolve_scope(scope)
    with _health_lock:
        if s is not None:
            _scope_entry(s)["quarantined"] = int(count)
        else:
            _health_quarantined = int(count)


def set_cell(name: str) -> None:
    """Publish this process's cell assignment to /healthz ("" =
    uncelled single-fleet deploy) — doc/design/multi-cell.md."""
    global _health_cell
    with _health_lock:
        _health_cell = str(name or "")


def set_mesh_devices(devices: int) -> None:
    """Publish the scheduler's device-mesh size to /healthz (1 =
    single-device; doc/design/multichip-shard.md) — a probe triaging
    a capacity page reads how many devices the solve shards over
    without scraping /metrics."""
    global _health_mesh_devices
    with _health_lock:
        _health_mesh_devices = int(devices)


def set_cell_peer_visible(visible: bool | None,
                          scope: str | None = None) -> None:
    """Publish whether PEER-cell evidence is currently visible on a
    live watch stream: true = foreign-cell objects observed and the
    stream is up; false = stream dead or no foreign evidence since
    reconnect; null = not a celled deploy.  The "cell dark" runbook's
    discriminator: a fully partitioned cell reads false while its
    local process is otherwise healthy (doc/design/multi-cell.md)."""
    global _health_cell_peer_visible
    s = _resolve_scope(scope)
    with _health_lock:
        if s is not None:
            _scope_entry(s)["cell_peer_visible"] = visible
        else:
            _health_cell_peer_visible = visible


def note_reclaim_outcome(outcome: str) -> None:
    """One resolved cross-cell claim (granted | rolled_back |
    expired) — the reclaim_claims_total counter's single funnel, so
    the manual claim path and the autopilot count identically."""
    reclaim_claims.inc(outcome)


def set_pending_demand(demand: dict | None,
                       scope: str | None = None) -> None:
    """Publish the cell's pending-demand column (autopilot/signal.py
    `DemandSignal.as_dict()`) to /healthz + /debug/fleet: pending
    pods + gangs with their aggregate requested cpu/mem/device — the
    exact signal the autopilot acts on, visible to operators even
    when the autopilot is off.  Keys appear only once published: a
    daemon that never computes the signal serves an unchanged body."""
    global _health_demand
    s = _resolve_scope(scope)
    with _health_lock:
        if s is not None:
            _scope_entry(s)["demand"] = dict(demand or {})
        else:
            _health_demand = dict(demand) if demand else None


def set_autopilot_state(state: dict | None,
                        scope: str | None = None) -> None:
    """Publish the autopilot's ladder rung + claim counters
    (autopilot/rebalancer.py `Autopilot.state()`) to /healthz +
    /debug/fleet — the "fleet is rebalancing, why?" runbook's first
    read."""
    global _health_autopilot
    s = _resolve_scope(scope)
    with _health_lock:
        if s is not None:
            _scope_entry(s)["autopilot"] = dict(state or {})
        else:
            _health_autopilot = dict(state) if state else None


def set_mesh_state(state: dict | None, scope: str | None = None) -> None:
    """Publish the mesh degradation ladder's live state (guardrails/
    mesh.py — configured devices, live rung + its device count, rung
    transitions) to /healthz + /debug/fleet — the "mesh shrank, why?"
    runbook's first read (doc/design/daemon-operations.md).  Keys
    appear only once published: a single-device daemon serves an
    unchanged body."""
    global _health_mesh
    s = _resolve_scope(scope)
    with _health_lock:
        if s is not None:
            _scope_entry(s)["mesh"] = dict(state or {})
        else:
            _health_mesh = dict(state) if state else None


def reset_health_scopes() -> None:
    """Drop every per-scope health entry (test / engine teardown)."""
    global _health_demand, _health_autopilot, _health_mesh
    with _health_lock:
        _health_scopes.clear()
        _health_demand = None
        _health_autopilot = None
        _health_mesh = None


def health_snapshot() -> dict[str, dict]:
    """Every scope's health fields, keyed by scope name ("" = the
    process-global daemon) — the fleet pane's in-process read
    (trace/fleet.py).  The "" entry mirrors the /healthz top level;
    scoped entries carry their own backlog fields."""
    with _health_lock:
        out = {
            "": {
                "state": _health_state,
                "role": _health_role,
                "epoch": _health_epoch,
                "quarantined": _health_quarantined,
                "cell": _health_cell,
                "cell_peer_visible": _health_cell_peer_visible,
                "ingest_lag_seconds": round(_health_ingest_lag, 3),
            },
            **{name: dict(entry)
               for name, entry in sorted(_health_scopes.items())},
        }
        if _health_demand is not None:
            out[""]["demand"] = dict(_health_demand)
        if _health_autopilot is not None:
            out[""]["autopilot"] = dict(_health_autopilot)
        if _health_mesh is not None:
            out[""]["mesh"] = dict(_health_mesh)
    out[""]["commit_queue_depth"] = int(commit_queue_depth.value())
    return out


def quarantined() -> int:
    with _health_lock:
        return _health_quarantined


def set_ingest_lag(seconds: float, scope: str | None = None) -> None:
    """Publish the freshest ingest lag (age of the newest applied
    watch event) to /healthz — probes see backlog pressure without
    scraping and parsing the `ingest_lag_seconds` histogram.  Set by
    the batched ingest applier on every applied batch; resolved
    through the CALLER'S scope (the applier thread binds its owning
    scheduler's) so two in-process schedulers never report each
    other's backlog."""
    global _health_ingest_lag
    s = _resolve_scope(scope)
    with _health_lock:
        if s is not None:
            _scope_entry(s)["ingest_lag_seconds"] = round(
                float(seconds), 3
            )
        else:
            _health_ingest_lag = float(seconds)


def set_commit_queue_depth(depth: int, scope: str | None = None) -> None:
    """Publish the commit pipeline's queued+running depth.  The
    process-global gauge always updates (single-scheduler /metrics
    behavior unchanged); under a bound scope the caller's /healthz
    "cells" entry additionally carries ITS OWN depth — the scoped
    read the fleet pane and the cells chaos/bench drives consume."""
    commit_queue_depth.set(float(depth))
    s = _resolve_scope(scope)
    if s is not None:
        with _health_lock:
            _scope_entry(s)["commit_queue_depth"] = int(depth)


def health_body() -> bytes:
    """The /healthz response body: one JSON object carrying the
    guardrail ladder state, election role + fencing epoch, and the
    health ledger's quarantined-node count.  (Plain-text "ok" grew
    fields in the failover PR; probes matching the old body should
    switch to `.state`.)"""
    import json

    with _health_lock:
        body = {
            "state": _health_state,
            "role": _health_role,
            "epoch": _health_epoch,
            "quarantined": _health_quarantined,
            # Backlog-pressure reads for probes: the freshest applied-
            # batch ingest lag and the commit pipeline's current
            # queued+running depth — both already exist as /metrics
            # series; here they are one cheap GET away for a liveness
            # probe or a runbook's first look.
            "ingest_lag_seconds": round(_health_ingest_lag, 3),
            # Cell identity + peer visibility (doc/design/
            # multi-cell.md): probes triaging a "cell dark" page
            # distinguish a partitioned cell (healthy process,
            # cell_peer_visible false) from a dead leader (no
            # response at all) from a breaker-open one (state
            # degraded, peer still visible).
            "cell": _health_cell,
            "cell_peer_visible": _health_cell_peer_visible,
            # Device-mesh size (doc/design/multichip-shard.md): how
            # many devices the solve shards over (1 = single-device).
            "mesh_devices": _health_mesh_devices,
        }
        # Demand + autopilot columns appear only once published
        # (--autopilot observe|on): probes of a daemon without the
        # subsystem see a byte-unchanged body.
        if _health_demand is not None:
            body["demand"] = dict(_health_demand)
        if _health_autopilot is not None:
            body["autopilot"] = dict(_health_autopilot)
        # Mesh degradation-ladder entry (guardrails/mesh.py): appears
        # only once a mesh-enabled scheduler publishes — a shrunken
        # mesh is visible to probes without scraping /metrics.
        if _health_mesh is not None:
            body["mesh"] = dict(_health_mesh)
        if _health_scopes:
            body["cells"] = {
                name: dict(entry)
                for name, entry in sorted(_health_scopes.items())
            }
    body["commit_queue_depth"] = int(commit_queue_depth.value())
    # Compile-ladder pressure (doc/design/compile-artifacts.md): a
    # probe or runbook's first question during a slow-cycle incident
    # is "is the daemon waiting on the compile service" — both already
    # exist as /metrics gauges; here they are one cheap GET away.
    body["compile_inflight"] = int(compile_inflight.value())
    body["warm_queue_depth"] = int(warm_queue_depth.value())
    return json.dumps(body, sort_keys=True).encode()


def serve(address: str = ":8080") -> threading.Thread:
    """Serve /metrics (+ /healthz and the /debug observability
    surface) on `address` (≙ --listen-address), daemon thread.

    Raises RuntimeError with a clear, flag-naming message when the
    port cannot be bound (most commonly: another daemon instance is
    already serving on it) — the old behavior was a raw OSError
    traceback out of the listener setup, which cost operators a
    debugging round trip to map back to --listen-address."""
    host, _, port = address.rpartition(":")

    registry = REGISTRY

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib API)
            if self.path.startswith("/debug"):
                # Always-on observability (kube_batch_tpu/trace/):
                # per-pod decision stories, cycle summaries, the
                # flight-recorder dump and the Chrome span trace.
                # Lazy import: metrics must stay importable without
                # the trace package loaded.
                import json as _json

                from kube_batch_tpu import trace as _trace

                status, payload = _trace.debug_http(self.path)
                body = _json.dumps(
                    payload, sort_keys=True, default=str
                ).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path == "/healthz":
                # Liveness for supervisors/load-balancers (the
                # deployment runbook's systemd watchdog target): the
                # listener thread answering at all is the LIVE signal;
                # the body carries the guardrail watchdog's ladder
                # state (ok | degraded | overloaded) so runbooks and
                # probes see degradation without scraping /metrics.
                # Always 200: a degraded daemon is still the leader
                # and must not be LB-evicted into a failover storm.
                # Body: {"state": ..., "role": ..., "epoch": N}.
                body = health_body()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            if self.path != "/metrics":
                self.send_error(404)
                return
            body = registry.expose().encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # silence per-request stderr noise
            return

    try:
        server = http.server.ThreadingHTTPServer(
            (host or "", int(port)), Handler
        )
    except OSError as exc:
        raise RuntimeError(
            f"metrics listener could not bind --listen-address "
            f"{address!r}: {exc} (most likely another kube-batch-tpu "
            "instance — or some other process — is already serving on "
            "this port; pick a different --listen-address, or pass an "
            "empty one to disable the listener)"
        ) from exc
    thread = threading.Thread(target=server.serve_forever, daemon=True)
    thread.server = server  # type: ignore[attr-defined] — for tests/shutdown
    thread.start()
    return thread
