"""The reclaim hysteresis ladder: observe → armed → claiming →
cooldown.

Borrowed from the guardrails watchdog's escalation ladder (one rung at
a time, streaks not instants, cooldown before re-escalation) and
pointed at capacity claims.  The structural no-flap argument:

* a claim requires ``arm_after`` consecutive pressured evaluations to
  ARM plus one more to FIRE — an oscillating signal that dips below
  the threshold every other tick resets the streak and never arms;
* at most one claim is in flight: the CLAIMING rung evaluates to
  "do nothing" until the claim resolves on the wire (granted, rolled
  back, or expired-fractional);
* every resolution enters COOLDOWN for ``cooldown_ticks`` evaluations
  — a second claim can never be issued within the cooldown of the
  first, so a claim is never "reversed within its cooldown" (the
  zero-flap acceptance check in scripts/check_chaos_autopilot.py);
* the ladder RELEASES (returns to observe) only after ``quiet_after``
  consecutive quiet evaluations while armed — one quiet blip under
  sustained pressure does not disarm it.

The rung survives a restart through the statestore: ``export_state``
rides the journal, and ``restore_state`` deliberately degrades a
persisted CLAIMING rung to a full COOLDOWN — the restarted leader no
longer knows its claim id, and re-claiming immediately could
double-claim against a grant that is already in flight.  The TTL'd
protocol guarantees the orphaned claim resolves on its own.
"""

from __future__ import annotations

OBSERVE = "observe"
ARMED = "armed"
CLAIMING = "claiming"
COOLDOWN = "cooldown"

_RUNGS = (OBSERVE, ARMED, CLAIMING, COOLDOWN)


class ReclaimLadder:
    def __init__(self, arm_after: int = 2, quiet_after: int = 2,
                 cooldown_ticks: int = 3) -> None:
        self.arm_after = max(int(arm_after), 1)
        self.quiet_after = max(int(quiet_after), 1)
        self.cooldown_ticks = max(int(cooldown_ticks), 1)
        self.rung = OBSERVE
        self.pressure_streak = 0
        self.quiet_streak = 0
        self.cooldown_left = 0
        self.transitions = 0
        self.last_transition: str | None = None

    # -- internal ----------------------------------------------------
    def _move(self, rung: str, why: str) -> None:
        if rung == self.rung:
            return
        self.last_transition = f"{self.rung}->{rung}:{why}"
        self.rung = rung
        self.transitions += 1
        self.pressure_streak = 0
        self.quiet_streak = 0

    # -- the per-cycle evaluation -------------------------------------
    def evaluate(self, pressured: bool) -> bool:
        """Advance one evaluation; True means "issue a claim NOW".
        Returning True does NOT move the rung — the caller reports the
        wire outcome via claim_opened() (claim exists) or nothing (no
        donor / wire error: still armed, retried next evaluation)."""
        if self.rung == OBSERVE:
            if pressured:
                self.pressure_streak += 1
                if self.pressure_streak >= self.arm_after:
                    self._move(ARMED, "sustained-pressure")
            else:
                self.pressure_streak = 0
            return False
        if self.rung == ARMED:
            if pressured:
                self.quiet_streak = 0
                return True
            self.quiet_streak += 1
            if self.quiet_streak >= self.quiet_after:
                self._move(OBSERVE, "sustained-quiet")
            return False
        if self.rung == CLAIMING:
            # One claim in flight: nothing to decide until the wire
            # resolves it (resolve()) — the no-double-claim guarantee.
            return False
        # COOLDOWN: count down; at expiry re-arm under pressure (the
        # re-claim path after a rollback) or stand down.
        self.cooldown_left -= 1
        if self.cooldown_left <= 0:
            self._move(ARMED if pressured else OBSERVE,
                       "cooldown-expired")
        return False

    # -- claim lifecycle reports ---------------------------------------
    def claim_opened(self) -> None:
        """The claimCapacity call succeeded: a claim is in flight."""
        self._move(CLAIMING, "claim-opened")

    def resolve(self, outcome: str) -> None:
        """The in-flight claim reached a terminal state on the wire
        (granted / rolled_back / expired).  Every outcome cools down:
        after a grant the new capacity needs cycles to absorb demand,
        and after a rollback hammering a dark donor helps nobody."""
        if self.rung != CLAIMING:
            return
        self.cooldown_left = self.cooldown_ticks
        self._move(COOLDOWN, outcome)

    # -- persistence ----------------------------------------------------
    def export_state(self) -> dict:
        return {
            "rung": self.rung,
            "pressure_streak": self.pressure_streak,
            "quiet_streak": self.quiet_streak,
            "cooldown_left": self.cooldown_left,
        }

    def restore_state(self, state: dict) -> str:
        """Adopt a journaled rung; tolerant of junk (cold start).
        A persisted CLAIMING rung degrades to a FULL cooldown: the
        claim id did not survive the restart, and the TTL will resolve
        the orphan — re-claiming before it does could double-claim."""
        rung = state.get("rung")
        if rung not in _RUNGS:
            return f"ignored unknown rung {rung!r}"
        if rung == CLAIMING:
            self.rung = COOLDOWN
            self.cooldown_left = self.cooldown_ticks
            self.pressure_streak = self.quiet_streak = 0
            self.last_transition = "claiming->cooldown:restart"
            return "claiming degraded to cooldown (restart safety)"
        self.rung = rung
        self.pressure_streak = max(int(state.get("pressure_streak", 0)), 0)
        self.quiet_streak = max(int(state.get("quiet_streak", 0)), 0)
        self.cooldown_left = max(int(state.get("cooldown_left", 0)), 0)
        if self.rung == COOLDOWN and self.cooldown_left <= 0:
            self.cooldown_left = self.cooldown_ticks
        return f"adopted rung {self.rung}"
