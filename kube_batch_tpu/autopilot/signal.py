"""The demand/pressure signal: what the autopilot (and an operator at
/debug/fleet) sees of one cell's backlog.

Demand is CONSTRAINT-SHAPED, not a pod count: a cell with 40 pending
best-effort singletons is healthy; a cell with one pending 14-member
gang whose aggregate cpu exceeds the whole cell's allocatable is
structurally starved — no amount of waiting places it.  The signal
therefore carries the aggregate requested resource vector of the
pending set (cpu / memory / accelerator devices), the gang count, and
the cell's own capacity + usage, all read from the scheduler's cache
mirror under one lock hold.
"""

from __future__ import annotations

import dataclasses
import math

from kube_batch_tpu.api.types import TaskStatus

#: Requested-resource keys that are neither cpu/memory nor the pods
#: count are accelerator devices (google.com/tpu, nvidia.com/gpu, …) —
#: summed into one "device" axis for the demand vector.
_NON_DEVICE_KEYS = ("cpu", "memory", "pods")

#: Statuses that hold capacity on a node (the "used" side of the
#: signal) — matches the donor duty's resident set.
_PLACED = (TaskStatus.BINDING, TaskStatus.BOUND, TaskStatus.RUNNING)


@dataclasses.dataclass(frozen=True)
class DemandSignal:
    """One cell's demand/capacity snapshot (all resource quantities in
    the cache's native units: milli-cpu, bytes, device count)."""

    pending_pods: int = 0
    pending_gangs: int = 0
    pending_cpu_milli: float = 0.0
    pending_mem_bytes: float = 0.0
    pending_device: float = 0.0
    used_cpu_milli: float = 0.0
    alloc_cpu_milli: float = 0.0
    alloc_mem_bytes: float = 0.0
    nodes: int = 0

    @property
    def starved(self) -> bool:
        """Structural starvation: the pending set cannot fit even an
        EMPTY cell — pending demand exceeds total allocatable on the
        cpu or memory axis.  This is the same predicate the chaos
        engine's manual claim duty uses, vector-widened."""
        return (self.pending_cpu_milli > self.alloc_cpu_milli
                or self.pending_mem_bytes > self.alloc_mem_bytes)

    @property
    def utilization(self) -> float:
        """cpu demand / allocatable — the donor-ranking axis."""
        if self.alloc_cpu_milli <= 0:
            return 0.0
        return self.used_cpu_milli / self.alloc_cpu_milli

    def nodes_needed(self, per_node_cpu_milli: float,
                     cap: int = 1) -> int:
        """How many donor nodes close the cpu deficit (pending beyond
        this cell's free capacity), clamped to [1, cap].  Fractional
        grants mean asking for the full deficit is safe: a donor that
        can only afford part of it still moves that part."""
        free = max(self.alloc_cpu_milli - self.used_cpu_milli, 0.0)
        deficit = self.pending_cpu_milli - free
        if deficit <= 0 or per_node_cpu_milli <= 0:
            return 1
        return max(1, min(int(math.ceil(deficit / per_node_cpu_milli)),
                          max(cap, 1)))

    def as_dict(self) -> dict:
        """The /healthz + /debug/fleet demand column."""
        return {
            "pending_pods": self.pending_pods,
            "pending_gangs": self.pending_gangs,
            "pending_cpu_milli": round(self.pending_cpu_milli, 3),
            "pending_mem_bytes": round(self.pending_mem_bytes, 3),
            "pending_device": round(self.pending_device, 3),
            "used_cpu_milli": round(self.used_cpu_milli, 3),
            "alloc_cpu_milli": round(self.alloc_cpu_milli, 3),
            "alloc_mem_bytes": round(self.alloc_mem_bytes, 3),
            "nodes": self.nodes,
            "starved": self.starved,
            "utilization": round(self.utilization, 4),
        }


def demand_signal(cache) -> DemandSignal:
    """Compute the cell's demand signal from its cache mirror under
    one lock hold — O(pods + nodes), run once per cycle on the leader
    (never in the hot packing path)."""
    pending_pods = 0
    pending_cpu = pending_mem = pending_dev = 0.0
    used_cpu = 0.0
    gangs: set[str] = set()
    with cache.lock():
        alloc_cpu = alloc_mem = 0.0
        nodes = 0
        for info in cache._nodes.values():
            alloc_cpu += float(info.node.allocatable.get("cpu", 0.0))
            alloc_mem += float(info.node.allocatable.get("memory", 0.0))
            nodes += 1
        for p in cache._pods.values():
            cpu = float(p.request.get("cpu", 0.0))
            if p.status == TaskStatus.PENDING:
                pending_pods += 1
                pending_cpu += cpu
                pending_mem += float(p.request.get("memory", 0.0))
                pending_dev += sum(
                    float(v) for k, v in p.request.items()
                    if k not in _NON_DEVICE_KEYS
                )
                if p.group:
                    gangs.add(p.group)
            elif p.status in _PLACED:
                used_cpu += cpu
    return DemandSignal(
        pending_pods=pending_pods,
        pending_gangs=len(gangs),
        pending_cpu_milli=pending_cpu,
        pending_mem_bytes=pending_mem,
        pending_device=pending_dev,
        used_cpu_milli=used_cpu,
        alloc_cpu_milli=alloc_cpu,
        alloc_mem_bytes=alloc_mem,
        nodes=nodes,
    )
