"""The per-cell Autopilot: the rebalancer that runs on the LEADER
after each scheduling cycle and closes the sensor→actuator loop.

One ``step()`` per cycle does, in order:

1. SENSE   — compute the cell's demand signal from its cache mirror
   and publish it to the scoped health registry (the /healthz +
   /debug/fleet demand column; visible even in ``observe`` mode).
2. DONATE  — serve the donor side of the reclaim protocol: discover
   pending claims naming this cell, and free ONE node per step through
   the normal evict seam (gang-atomically), guarded by donor-side
   headroom — a donor never drains below its own demand + headroom.
3. RESOLVE — poll this cell's own in-flight claim (claimant-role
   listClaims) and feed the terminal outcome to the ladder + the
   ``reclaim_claims_total{outcome}`` counter.
4. DECIDE  — evaluate the hysteresis ladder against the pressure
   predicate (structural starvation AND sustained SLO fast-burn) and,
   when it fires, issue one multi-node ``claimCapacity`` against the
   least-utilized donor.

Every wire interaction is the SAME epoch-fenced protocol the manual
path uses: a stale leader's claim bounces off the fence, a partition
mid-claim rolls back on TTL to exactly nothing.  The step is wrapped
in try/except at its call sites — an autopilot bug degrades to "no
rebalancing", never to a broken scheduling cycle.
"""

from __future__ import annotations

import dataclasses
import logging

from kube_batch_tpu import metrics
from kube_batch_tpu import trace as trace_obs
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.autopilot.ladder import ReclaimLadder
from kube_batch_tpu.autopilot.signal import DemandSignal, demand_signal
from kube_batch_tpu.trace import context as trace_ctx

log = logging.getLogger(__name__)

_RESIDENT = (TaskStatus.BINDING, TaskStatus.BOUND, TaskStatus.RUNNING)


@dataclasses.dataclass(frozen=True)
class AutopilotConfig:
    """Thresholds; defaults match the chaos scenario's tick scale —
    the daemon flags (--autopilot-*) override for wall-clock cycles."""

    #: "observe" publishes the demand column + ladder state but never
    #: claims and never donates; "on" is the full loop.
    mode: str = "on"
    #: Donor cells this cell may claim from (never itself).
    donors: tuple = ()
    arm_after: int = 2
    quiet_after: int = 2
    cooldown_ticks: int = 3
    claim_ttl_ticks: int = 3
    #: Upper bound on nodes per claim — one claim burst is bounded
    #: even against an unbounded deficit; fractional grants cover the
    #: rest on the next armed cycle.
    max_nodes_per_claim: int = 2
    #: cpu (milli) the donor keeps free beyond its own demand before
    #: it will donate a node.
    headroom_cpu_milli: float = 0.0
    #: Pressure requires an SLO fast-burn reading, not just structural
    #: starvation (False = structural-only, for benches without an
    #: SLO engine).
    require_slo_burn: bool = True
    #: Which objective must burn ("" = any objective).
    slo_objective: str = "placement"
    #: The burn sensor is bursty (a sliding window slides); a burn
    #: reading stays "fresh" for this many steps when joined with
    #: still-starved demand.
    burn_memory: int = 3


class Autopilot:
    def __init__(self, cache, backend, cell: str,
                 config: AutopilotConfig, *, evict=None, slo=None,
                 is_leader=None) -> None:
        self.cache = cache
        self.backend = backend
        self.cell = cell
        self.config = config
        self._evict = evict
        #: Callable returning the cell's SloEngine (or None) — a
        #: callable because the engine is armed after construction.
        self._slo = slo
        self._is_leader = is_leader
        self.ladder = ReclaimLadder(config.arm_after, config.quiet_after,
                                    config.cooldown_ticks)
        self.claim_inflight: int | None = None
        self.counters = {"claims": 0, "granted": 0, "rolled_back": 0,
                         "expired": 0, "donations": 0}
        self.last_signal: DemandSignal | None = None
        self._burn_age: int | None = None  # steps since last burn read

    # -- persistence (rides the statestore journal) --------------------
    def export_state(self) -> dict:
        return {"ladder": self.ladder.export_state()}

    def restore_state(self, state: dict) -> str:
        return self.ladder.restore_state(state.get("ladder") or {})

    # -- the per-cycle step ---------------------------------------------
    def step(self) -> dict:
        """One sense→donate→resolve→decide pass; returns a record of
        what happened (empty when nothing did).  Leader-gated: a
        follower publishes nothing and touches no wire."""
        rec: dict = {}
        if self._is_leader is not None and not self._is_leader():
            return rec
        sig = demand_signal(self.cache)
        self.last_signal = sig
        metrics.set_pending_demand(sig.as_dict())
        if self.config.mode == "on":
            self._donor_step(sig, rec)
            self._resolve_step(rec)
            pressured = self._pressured(sig)
            if self.ladder.evaluate(pressured):
                self._claim_step(sig, rec)
        metrics.set_autopilot_state(self.state())
        return rec

    def state(self) -> dict:
        """The /healthz + /debug/fleet autopilot column."""
        return {
            "mode": self.config.mode,
            "rung": self.ladder.rung,
            "claim_inflight": self.claim_inflight,
            "transitions": self.ladder.transitions,
            **self.counters,
        }

    # -- pressure ---------------------------------------------------------
    def _pressured(self, sig: DemandSignal) -> bool:
        """Sustained-pressure INPUT (the ladder supplies "sustained"):
        structurally starved AND the SLO burn gate agrees."""
        if not sig.starved:
            return False
        return self._slo_gate()

    def _slo_gate(self) -> bool:
        if not self.config.require_slo_burn:
            return True
        eng = self._slo() if callable(self._slo) else self._slo
        if eng is None:
            # No engine armed (tracing off): structural starvation
            # stands alone — the ladder still demands it be sustained.
            return True
        burning = eng.fast_burning(self.config.slo_objective or None)
        if burning:
            self._burn_age = 0
            return True
        # The burn window slides: demand that stays starved keeps a
        # recent burn reading fresh for burn_memory steps, so a
        # one-tick sensor dip cannot disarm a real starvation episode.
        if self._burn_age is not None:
            self._burn_age += 1
            if self._burn_age <= self.config.burn_memory:
                return True
            self._burn_age = None
        return False

    # -- claimant side ------------------------------------------------
    def _resolve_step(self, rec: dict) -> None:
        """Poll the in-flight claim for a terminal state (claimant-role
        listClaims) and settle the ladder + counters."""
        if self.claim_inflight is None:
            return
        try:
            claims = self.backend.list_claims(role="claimant")
        except (ConnectionError, TimeoutError):
            return  # partitioned: the TTL is already running
        claim = next((c for c in claims
                      if c.get("id") == self.claim_inflight), None)
        if claim is None or claim.get("state") == "pending":
            return
        state = str(claim.get("state"))
        if state == "rolled-back":
            outcome = "rolled_back"
        elif claim.get("fractional"):
            outcome = "expired"  # partial fill closed at TTL
        else:
            outcome = "granted"
        granted = claim.get("granted") or (
            [claim["node"]] if claim.get("node") else [])
        self.counters[outcome] += 1
        metrics.note_reclaim_outcome(outcome)
        trace_obs.note_transition(
            "reclaim-resolve", claim=claim.get("id"), cell=self.cell,
            outcome=outcome, granted=len(granted),
        )
        self.ladder.resolve(outcome)
        self.claim_inflight = None
        rec["resolved"] = {"claim": claim.get("id"), "outcome": outcome,
                           "granted": list(granted)}

    def _claim_step(self, sig: DemandSignal, rec: dict) -> None:
        donor = self._pick_donor()
        if donor is None:
            rec["claim-error"] = "no-donor"
            return
        per_node = (sig.alloc_cpu_milli / sig.nodes) if sig.nodes else 0.0
        nodes = sig.nodes_needed(per_node, self.config.max_nodes_per_claim)
        try:
            # The claim is the ORIGIN of a cross-scheduler flow: its
            # traceparent rides the request and the donor's drain +
            # offer stitch under the same trace id.
            with trace_obs.flow("reclaim-claim", cell=self.cell,
                                donor=donor):
                cid = self.backend.claim_capacity(
                    donor, nodes=nodes,
                    ttl_ticks=self.config.claim_ttl_ticks,
                )
        except (ConnectionError, TimeoutError):
            rec["claim-error"] = "unreachable"  # still armed: retried
            return
        except RuntimeError as exc:
            rec["claim-error"] = str(exc)[:200]
            return
        self.claim_inflight = cid
        self.counters["claims"] += 1
        self.ladder.claim_opened()
        trace_obs.note_transition(
            "reclaim-claim", claim=cid, cell=self.cell, donor=donor,
            nodes=nodes,
        )
        rec["claim"] = {"claim": cid, "from": donor, "nodes": nodes}

    def _pick_donor(self) -> str | None:
        """Least-utilized donor first, from whatever demand columns
        this process can see (in-process scopes in the chaos drive /
        bench; a lone daemon falls back to configured order)."""
        donors = [d for d in self.config.donors if d != self.cell]
        if not donors:
            return None
        snap = metrics.health_snapshot()

        def util(item):
            idx, name = item
            demand = (snap.get(name) or {}).get("demand") or {}
            u = demand.get("utilization")
            return (float(u) if u is not None else 0.5, idx)

        return sorted(enumerate(donors), key=util)[0][1]

    # -- donor side -----------------------------------------------------
    def _donor_step(self, sig: DemandSignal, rec: dict) -> None:
        """Serve one node of the oldest pending claim naming this
        cell, gang-atomically, iff the cell can afford it."""
        try:
            claims = self.backend.list_claims()
        except (ConnectionError, TimeoutError):
            return  # partitioned: the claim rolls back on TTL
        claims = [c for c in claims if c.get("state") == "pending"]
        if not claims:
            return
        claim = claims[0]
        total = sig.pending_cpu_milli + sig.used_cpu_milli
        with self.cache.lock():
            nodes = sorted(
                (info.node for info in self.cache._nodes.values()),
                key=lambda n: n.name,
            )
            residents: dict[str, list] = {n.name: [] for n in nodes}
            for p in self.cache._pods.values():
                if p.node in residents and p.status in _RESIDENT:
                    residents[p.node].append(p)
            # The eviction CLOSURE per node: every placed member of
            # every gang resident on it (gang-atomicity — no gang is
            # ever stranded half-on donated hardware).  Cheapest
            # closure first: an empty node donates for free, and the
            # fewer pods drained, the less the donor's own next cycle
            # churns re-placing them.
            closures: dict[str, list] = {}
            for node in nodes:
                groups = {p.group for p in residents[node.name]
                          if p.group}
                closures[node.name] = sorted(
                    (
                        p for p in self.cache._pods.values()
                        if (p.group in groups
                            or p in residents[node.name])
                        and p.node is not None
                        and p.status in _RESIDENT
                    ),
                    key=lambda p: p.uid,
                )
        candidates = sorted(
            nodes, key=lambda n: (len(closures[n.name]), n.name)
        )
        for node in candidates:
            node_cpu = float(node.allocatable.get("cpu", 0.0))
            if total + self.config.headroom_cpu_milli > \
                    sig.alloc_cpu_milli - node_cpu:
                continue  # headroom guard: cannot afford this node
            victims = closures[node.name]
            victim_nodes = {p.uid: p.node for p in victims}
            # Donor side of the stitched flow: adopt the claimant's
            # propagated context so drain + offer record under the
            # claim's trace id.
            parent = trace_ctx.parse(claim.get("traceparent"))
            try:
                with trace_obs.flow(
                    "reclaim-donate", ctx=parent, cell=self.cell,
                    claim=claim["id"], node=node.name,
                ):
                    for pod in victims:
                        if self._evict is not None:
                            self._evict(pod, "reclaim-donate")
                    self.backend.offer_capacity(claim["id"], node.name)
            except (ConnectionError, TimeoutError):
                return  # partitioned mid-donation: rolls back on TTL
            except RuntimeError as exc:
                log.warning("%s: donation refused: %s", self.cell, exc)
                return
            dlog = trace_obs.decision_log()
            if dlog is not None:
                for pod in victims:
                    dlog.note_eviction(
                        pod.uid, pod.name, pod.group,
                        victim_nodes.get(pod.uid),
                        "reclaim-donate",
                        trace_obs.current_cycle(),
                    )
            self.counters["donations"] += 1
            trace_obs.note_transition(
                "reclaim-offer", claim=claim["id"], cell=self.cell,
                node=node.name, evicted=len(victims),
            )
            rec["donation"] = {"claim": claim["id"], "node": node.name,
                               "evicted": len(victims)}
            return
        rec["donate-skipped"] = {"claim": claim["id"],
                                 "reason": "headroom"}
