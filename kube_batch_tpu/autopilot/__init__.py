"""Fleet autopilot: close the loop from SLO burn to capacity claims.

PR 12 gave the fleet a negotiated cross-cell reclaim protocol
(epoch-fenced ``claimCapacity`` / ``offerCapacity`` with TTL'd
rollback) and PR 13 gave it per-cell SLO burn rates — but the two were
never connected: an operator read ``/debug/fleet``, saw cell A burning
its placement SLO with a mountain of pending gangs, and typed a
``claimCapacity`` by hand.  This package is that operator, automated
and made boring:

* ``signal``  — the demand/pressure signal: pending pods + gangs with
  their aggregate requested resource VECTOR (cpu / memory / devices),
  computed from the cell's own cache mirror.  Constraint-shaped
  demand, not raw pod counts ("Priority Matters", PAPERS.md).
* ``ladder``  — the hysteresis ladder (observe → armed → claiming →
  cooldown), borrowed from the guardrails watchdog: claims fire only
  from SUSTAINED pressure, at most one claim is in flight, and every
  resolution is followed by a cooldown — two cells can never
  ping-pong capacity (doc/design/fleet-autopilot.md § no-flap).
* ``rebalancer`` — the per-cell ``Autopilot`` that runs on the LEADER
  after each scheduling cycle: publishes the demand column to
  ``/healthz`` + ``/debug/fleet``, serves the donor side of pending
  claims (headroom-guarded, gang-atomic drains), resolves its own
  in-flight claim from the wire, and — when the ladder says so —
  issues a multi-node ``claimCapacity`` against the least-utilized
  donor.

Strictly decision-invisible when disabled: with ``--autopilot off``
(the default) nothing here is constructed and every existing chaos
hash reproduces byte-identical (scripts/check_chaos_autopilot.py pins
it).
"""

from kube_batch_tpu.autopilot.ladder import ReclaimLadder
from kube_batch_tpu.autopilot.rebalancer import Autopilot, AutopilotConfig
from kube_batch_tpu.autopilot.signal import DemandSignal, demand_signal

__all__ = [
    "Autopilot",
    "AutopilotConfig",
    "DemandSignal",
    "ReclaimLadder",
    "demand_signal",
]
