"""Thread-bound subsystem scope for multi-scheduler-per-process runs.

PR 2 stopped a second constructed Scheduler from STOMPING the
process-global observability state (gauges, tracer); multi-cell
scale-out needs the stronger form: two LIVE schedulers in one process
(the 2-cell chaos drive, the bench aggregate section) must not
interleave their span trees, decision records, flight-recorder rings
or /healthz ladder states.  The fix is a per-scheduler SCOPE — the
cell name — bound to whichever thread is currently doing that
scheduler's work:

* the driving thread binds the cell's scope around `run_once`;
* a scheduler-owned worker thread (watch ingest applier, commit flush
  workers) binds its owner's scope once at thread start;
* the process-global facades (`kube_batch_tpu.trace`,
  `metrics.set_health_state` & friends) resolve the CURRENT scope
  first and fall back to the legacy process-global state when no
  scope is bound — single-scheduler processes see zero change.

Deliberately a leaf module (stdlib only): both `metrics` and `trace`
consume it, and neither may import the other.
"""

from __future__ import annotations

import threading

_local = threading.local()


def bind(name: str | None) -> None:
    """Bind the calling thread to scope `name` (None = unscoped: the
    legacy process-global state)."""
    _local.name = name


def current() -> str | None:
    return getattr(_local, "name", None)


class bound:
    """Context manager: bind a scope for the duration of a block and
    restore whatever was bound before (nesting-safe)."""

    __slots__ = ("name", "_prev")

    def __init__(self, name: str | None) -> None:
        self.name = name

    def __enter__(self) -> "bound":
        self._prev = current()
        bind(self.name)
        return self

    def __exit__(self, *exc) -> bool:
        bind(self._prev)
        return False
