"""Predicates plugin: vectorized node feasibility.

Reference counterpart: plugins/predicates/predicates.go — PredicateFn
chaining the upstream k8s predicates (MatchNodeSelector,
PodFitsHostPorts, PodToleratesNodeTaints, node condition/pressure
checks) per (task, node) pair, fanned out 16-way over nodes.

TPU-native redesign: every string-matching predicate becomes one matmul
over the snapshot's interned multi-hot vocabularies (see
api/snapshot.py), producing the whole bool[T, N] feasibility matrix in
a handful of MXU ops instead of T×N per-pair string comparisons:

* MatchNodeSelector  —  a node matches iff it carries EVERY selected
  label:      task_sel @ node_labelsᵀ  ==  Σ task_sel
* PodToleratesNodeTaints — feasible iff every node taint is tolerated:
  untolerated(t, n) = Σ_v node_taints[n,v] · (1 − task_tol[t,v]) == 0
* PodFitsHostPorts   —  no requested port already occupied:
  task_ports @ node_portsᵀ == 0
* node readiness     —  unready/unschedulable nodes are excluded (the
  reference's node-condition checks, collapsed to the packed
  `node_ready` bit; memory/disk/PID pressure arrive from the adapter
  the same way).

Resource fit is deliberately NOT here, exactly like the reference
(actions check `Resreq ⊑ Idle` themselves; see ops/assignment.py).

Arguments (≙ predicates.go's `predicate.*Enable` toggles):
    predicate.NodeSelectorEnable  (default true)
    predicate.TaintsEnable        (default true)
    predicate.HostPortsEnable     (default true)
    predicate.NodeReadyEnable     (default true)
"""

from __future__ import annotations

import jax.numpy as jnp

from kube_batch_tpu.framework.plugin import Plugin, register_plugin


@register_plugin
class PredicatesPlugin(Plugin):
    name = "predicates"

    def register(self, policy, tier: int) -> None:  # noqa: ARG002
        if not self.enabled_for("predicate"):
            return
        sel_on = self.args.get_bool("predicate.NodeSelectorEnable", True)
        tnt_on = self.args.get_bool("predicate.TaintsEnable", True)
        prt_on = self.args.get_bool("predicate.HostPortsEnable", True)
        rdy_on = self.args.get_bool("predicate.NodeReadyEnable", True)

        def predicate(snap):
            T, N = snap.num_tasks, snap.num_nodes
            ok = jnp.ones((T, N), bool)
            if sel_on:
                want = jnp.sum(snap.task_sel, axis=1, keepdims=True)  # f32[T,1]
                have = snap.task_sel @ snap.node_labels.T             # f32[T,N]
                ok = ok & (have >= want)
            if tnt_on:
                total = jnp.sum(snap.node_taints, axis=1)[None, :]    # f32[1,N]
                tolerated = snap.task_tol @ snap.node_taints.T        # f32[T,N]
                ok = ok & (total - tolerated <= 0.5)
            if prt_on:
                clash = snap.task_ports @ snap.node_ports.T           # f32[T,N]
                ok = ok & (clash <= 0.5)
            if rdy_on:
                ok = ok & snap.node_ready[None, :]
            return ok

        policy.add_predicate_fn(predicate)
