"""Predicates plugin: vectorized node feasibility.

Reference counterpart: plugins/predicates/predicates.go — PredicateFn
chaining the upstream k8s predicates (MatchNodeSelector,
PodFitsHostPorts, PodToleratesNodeTaints, node condition/pressure
checks) per (task, node) pair, fanned out 16-way over nodes.

TPU-native redesign: every string-matching predicate becomes one matmul
over the snapshot's interned multi-hot vocabularies (see
api/snapshot.py), producing the whole bool[T, N] feasibility matrix in
a handful of MXU ops instead of T×N per-pair string comparisons:

* MatchNodeSelector  —  a node matches iff it carries EVERY selected
  label:      task_sel @ node_labelsᵀ  ==  Σ task_sel
* PodToleratesNodeTaints — feasible iff every node taint is tolerated:
  untolerated(t, n) = Σ_v node_taints[n,v] · (1 − task_tol[t,v]) == 0
* PodFitsHostPorts   —  no requested port already occupied:
  task_ports @ node_portsᵀ == 0
* node readiness     —  unready/unschedulable nodes are excluded (the
  reference's node-condition checks, collapsed to the packed
  `node_ready` bit; memory/disk/PID pressure arrive from the adapter
  the same way).

Resource fit is deliberately NOT here, exactly like the reference
(actions check `Resreq ⊑ Idle` themselves; see ops/assignment.py).

Inter-pod affinity (the vendored k8s inter-pod affinity predicate in the
reference) is registered as a DYNAMIC predicate — placements made
earlier in the same cycle change feasibility, so it re-evaluates inside
every auction round / preemption step; see `pod_affinity_predicate`.

Arguments (≙ predicates.go's `predicate.*Enable` toggles):
    predicate.NodeSelectorEnable  (default true)
    predicate.TaintsEnable        (default true)
    predicate.HostPortsEnable     (default true)
    predicate.NodeReadyEnable     (default true)
    predicate.PodAffinityEnable   (default true)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import allocated_mask, status_is
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.framework.plugin import Plugin, register_plugin


@register_plugin
class PredicatesPlugin(Plugin):
    name = "predicates"

    def register(self, policy, tier: int) -> None:  # noqa: ARG002
        if not self.enabled_for("predicate"):
            return
        sel_on = self.args.get_bool("predicate.NodeSelectorEnable", True)
        tnt_on = self.args.get_bool("predicate.TaintsEnable", True)
        prt_on = self.args.get_bool("predicate.HostPortsEnable", True)
        rdy_on = self.args.get_bool("predicate.NodeReadyEnable", True)

        def predicate(snap):
            T, N = snap.num_tasks, snap.num_nodes
            ok = jnp.ones((T, N), bool)
            if sel_on:
                want = jnp.sum(snap.task_sel, axis=1, keepdims=True)  # f32[T,1]
                have = snap.task_sel @ snap.node_labels.T             # f32[T,N]
                ok = ok & (have >= want)
            if tnt_on:
                total = jnp.sum(snap.node_taints, axis=1)[None, :]    # f32[1,N]
                tolerated = snap.task_tol @ snap.node_taints.T        # f32[T,N]
                ok = ok & (total - tolerated <= 0.5)
            if prt_on:
                clash = snap.task_ports @ snap.node_ports.T           # f32[T,N]
                ok = ok & (clash <= 0.5)
            if rdy_on:
                ok = ok & snap.node_ready[None, :]
            return ok

        policy.add_predicate_fn(predicate)

        if self.args.get_bool("predicate.PodAffinityEnable", True):
            policy.add_dynamic_predicate_fn(
                pod_affinity_predicate, row_fn=pod_affinity_row
            )
            policy.add_global_serialize_fn(bootstrap_mask)


def resident_podlabels(snap, state):
    """(Hb, Ab): bool[N, K] label/anti-term presence among each node's
    residents.  "Resident" = allocated statuses or pipelined with a node
    — future-oriented, so a RELEASING victim no longer anchors affinity
    or blocks anti-affinity for placements that land after it leaves
    (consistent with FutureIdle reasoning)."""
    held = (
        (
            allocated_mask(state.task_state)
            | status_is(state.task_state, TaskStatus.PIPELINED)
        )
        & (state.task_node >= 0)
        & snap.task_mask
    )
    seg = jnp.where(held, state.task_node, snap.num_nodes)
    w = held.astype(snap.task_podlabels.dtype)[:, None]
    Hb = jax.ops.segment_sum(
        snap.task_podlabels * w, seg, num_segments=snap.num_nodes + 1
    )[: snap.num_nodes] > 0
    Ab = jax.ops.segment_sum(
        snap.task_anti * w, seg, num_segments=snap.num_nodes + 1
    )[: snap.num_nodes] > 0
    return Hb, Ab


def pod_affinity_predicate(snap, state):
    """bool[T, N] inter-pod affinity/anti-affinity feasibility
    (≙ the vendored k8s inter-pod affinity predicate in
    plugins/predicates/predicates.go, topologyKey = node):

    * required affinity: every term names a label some resident of the
      node carries — with the k8s bootstrap rule (a term no pod in the
      whole cluster matches is waived when the task itself carries the
      label, so the first gang member can land);
    * anti-affinity: no resident carries any of the task's anti terms;
    * symmetry: no resident's anti term matches the task's own labels.
    """
    Hb, Ab = resident_podlabels(snap, state)
    Hf = Hb.astype(snap.task_aff.dtype)

    need = jnp.sum(snap.task_aff, axis=1, keepdims=True)       # f32[T,1]
    have = snap.task_aff @ Hf.T                                # f32[T,N]
    term_exists = jnp.any(Hb, axis=0)                          # bool[K]
    # Bootstrap waiver (k8s rule): a term NO pod in the cluster matches
    # is waived for ANY task that itself carries the label.  The auction
    # keeps this sound in a batched round by accepting at most ONE
    # bootstrap-dependent placement per round (see bootstrap_mask below
    # and ops/assignment.py's global-serialize step) — after it lands,
    # the term exists and the rest must genuinely co-locate.
    bootstrap = jnp.sum(
        snap.task_aff * (snap.task_podlabels > 0) * (~term_exists)[None, :],
        axis=1,
        keepdims=True,
    )                                                          # f32[T,1]
    aff_ok = have + bootstrap >= need

    anti_hit = snap.task_anti @ Hf.T                           # f32[T,N]
    sym_hit = snap.task_podlabels @ Ab.astype(Hf.dtype).T      # f32[T,N]
    return aff_ok & (anti_hit <= 0.5) & (sym_hit <= 0.5)


def pod_affinity_row(snap, state, p):
    """bool[N]: pod_affinity_predicate for ONE task — O(N·K) instead of
    the full [T, N] matrix; used per preemption step."""
    Hb, Ab = resident_podlabels(snap, state)
    Hf = Hb.astype(snap.task_aff.dtype)
    aff = snap.task_aff[p]                                     # f32[K]
    own = snap.task_podlabels[p]
    term_exists = jnp.any(Hb, axis=0)
    need = jnp.sum(aff)
    have = Hf @ aff                                            # f32[N]
    bootstrap = jnp.sum(aff * (own > 0) * ~term_exists)
    aff_ok = have + bootstrap >= need
    anti_hit = Hf @ snap.task_anti[p]
    sym_hit = Ab.astype(Hf.dtype) @ own
    return aff_ok & (anti_hit <= 0.5) & (sym_hit <= 0.5)


def bootstrap_mask(snap, state):
    """bool[T]: pending tasks whose required affinity currently relies
    on the bootstrap waiver — at most one of these may be accepted per
    auction round (all of them placing at once would scatter a
    self-affine gang across nodes)."""
    Hb, _ = resident_podlabels(snap, state)
    term_exists = jnp.any(Hb, axis=0)
    return jnp.any(
        (snap.task_aff > 0) & (~term_exists)[None, :], axis=1
    ) & snap.task_mask
