"""Predicates plugin: vectorized node feasibility.

Reference counterpart: plugins/predicates/predicates.go — PredicateFn
chaining the upstream k8s predicates (MatchNodeSelector,
PodFitsHostPorts, PodToleratesNodeTaints, node condition/pressure
checks) per (task, node) pair, fanned out 16-way over nodes.

TPU-native redesign: every string-matching predicate becomes one matmul
over the snapshot's interned multi-hot vocabularies (see
api/snapshot.py), producing the whole bool[T, N] feasibility matrix in
a handful of MXU ops instead of T×N per-pair string comparisons:

* MatchNodeSelector  —  a node matches iff it carries EVERY selected
  label:      task_sel @ node_labelsᵀ  ==  Σ task_sel
* PodToleratesNodeTaints — feasible iff every node taint is tolerated:
  untolerated(t, n) = Σ_v node_taints[n,v] · (1 − task_tol[t,v]) == 0
* PodFitsHostPorts   —  no requested port already occupied:
  task_ports @ node_portsᵀ == 0
* node readiness     —  unready/unschedulable nodes are excluded (the
  reference's node-condition checks, collapsed to the packed
  `node_ready` bit; memory/disk/PID pressure arrive from the adapter
  the same way).

Resource fit is deliberately NOT here, exactly like the reference
(actions check `Resreq ⊑ Idle` themselves; see ops/assignment.py).

Inter-pod affinity (the vendored k8s inter-pod affinity predicate in the
reference) is registered as a DYNAMIC predicate — placements made
earlier in the same cycle change feasibility, so it re-evaluates inside
every auction round / preemption step; see `pod_affinity_predicate`.

Inter-pod affinity supports arbitrary topology keys ("zone:app=web"
terms): the packer interns (key, label) terms and node→domain indices,
and the resident aggregation here runs per DOMAIN instead of per node
(≙ the vendored predicate's topologyKey support).  Snapshots with no
topo terms carry zero-width topo tensors and skip the domain math at
trace time.

Arguments (≙ predicates.go's `predicate.*Enable` toggles):
    predicate.NodeSelectorEnable    (default true)
    predicate.TaintsEnable          (default true)
    predicate.HostPortsEnable       (default true)
    predicate.NodeReadyEnable       (default true)
    predicate.PodAffinityEnable     (default true)
    predicate.MemoryPressureEnable  (default false — opt-in, as upstream)
    predicate.DiskPressureEnable    (default false)
    predicate.PidPressureEnable     (default false)
    predicate.VolumeBindingEnable   (default true — PVC/StorageClass
                                     node feasibility, ≙ the VolumeBinder
                                     informers in cache.go)
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import allocated_mask, status_is
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.framework.plugin import Plugin, register_plugin


@register_plugin
class PredicatesPlugin(Plugin):
    name = "predicates"

    def register(self, policy, tier: int) -> None:  # noqa: ARG002
        if not self.enabled_for("predicate"):
            return
        sel_on = self.args.get_bool("predicate.NodeSelectorEnable", True)
        tnt_on = self.args.get_bool("predicate.TaintsEnable", True)
        prt_on = self.args.get_bool("predicate.HostPortsEnable", True)
        rdy_on = self.args.get_bool("predicate.NodeReadyEnable", True)
        # Pressure checks are opt-in, matching upstream's defaults: a
        # conf written for the reference that never mentions them gets
        # identical behavior here.
        pressure_on = (
            self.args.get_bool("predicate.MemoryPressureEnable", False),
            self.args.get_bool("predicate.DiskPressureEnable", False),
            self.args.get_bool("predicate.PidPressureEnable", False),
        )
        vol_on = self.args.get_bool("predicate.VolumeBindingEnable", True)

        def predicate(snap):
            T, N = snap.num_tasks, snap.num_nodes
            ok = jnp.ones((T, N), bool)
            if sel_on:
                want = jnp.sum(snap.task_sel, axis=1, keepdims=True)  # f32[T,1]
                have = snap.task_sel @ snap.node_labels.T             # f32[T,N]
                ok = ok & (have >= want)
            if tnt_on:
                total = jnp.sum(snap.node_taints, axis=1)[None, :]    # f32[1,N]
                tolerated = snap.task_tol @ snap.node_taints.T        # f32[T,N]
                ok = ok & (total - tolerated <= 0.5)
            if prt_on:
                clash = snap.task_ports @ snap.node_ports.T           # f32[T,N]
                ok = ok & (clash <= 0.5)
            if rdy_on:
                ok = ok & snap.node_ready[None, :]
            for dim, on in enumerate(pressure_on):
                if on:
                    ok = ok & (snap.node_pressure[None, :, dim] <= 0.5)
            if vol_on:
                # Volume feasibility (≙ the VolumeBinder's node filter):
                # bound local PVs pin to one node; unbound constrained
                # claims need >=1 allowed label per claim group.
                node_ids = jnp.arange(N, dtype=jnp.int32)
                pinned = snap.task_vol_node
                ok = ok & (
                    (pinned == -1)[:, None]
                    | (pinned[:, None] == node_ids[None, :])
                )
                if snap.task_vol_groups.shape[1]:  # static: groups exist
                    f = snap.task_vol_groups.dtype
                    node_ok_g = (
                        snap.node_labels @ snap.vol_group_sel.T
                    ) > 0.5                                      # [N, G]
                    miss = snap.task_vol_groups @ (
                        1.0 - node_ok_g.astype(f)
                    ).T                                          # [T, N]
                    ok = ok & (miss <= 0.5)
            return ok

        policy.add_predicate_fn(predicate)

        if self.args.get_bool("predicate.PodAffinityEnable", True):
            policy.add_dynamic_predicate_fn(
                pod_affinity_predicate,
                row_fn=pod_affinity_row,
                subset_fn=pod_affinity_subset,
            )
            policy.add_global_serialize_fn(bootstrap_mask)
            policy.add_domain_serialize_fn(topo_anti_participants)


def resident_podlabels(snap, state, include_releasing: bool = False):
    """(Hb, Ab): bool[N, K] label/anti-term presence among each node's
    residents.  "Resident" = allocated statuses or pipelined with a node
    — future-oriented, so a RELEASING victim no longer anchors affinity
    or blocks anti-affinity for placements that land after it leaves
    (consistent with FutureIdle reasoning).

    `include_releasing` widens the resident set to RELEASING tasks still
    on their node: an IMMEDIATE (Idle-pass) placement binds while such a
    victim may still be terminating, and anti-affinity is scheduler-
    enforced only — the reference's vendored predicate still sees the
    terminating pod in its node info and refuses (predicates.go)."""
    held = _resident_mask(snap, state, include_releasing)
    seg = jnp.where(held, state.task_node, snap.num_nodes)
    w = held.astype(snap.task_podlabels.dtype)[:, None]
    Hb = jax.ops.segment_sum(
        snap.task_podlabels * w, seg, num_segments=snap.num_nodes + 1
    )[: snap.num_nodes] > 0
    Ab = jax.ops.segment_sum(
        snap.task_anti * w, seg, num_segments=snap.num_nodes + 1
    )[: snap.num_nodes] > 0
    return Hb, Ab


def _resident_mask(snap, state, include_releasing: bool):
    held = (
        (
            allocated_mask(state.task_state)
            | status_is(state.task_state, TaskStatus.PIPELINED)
        )
        & (state.task_node >= 0)
        & snap.task_mask
    )
    if include_releasing:
        held = held | (
            status_is(state.task_state, TaskStatus.RELEASING)
            & (state.task_node >= 0)
            & snap.task_mask
        )
    return held


def resident_domain_labels(snap, state, include_releasing: bool = False):
    """(Hd, Ad): bool[D, K] label / anti-term-label presence among each
    topology DOMAIN's residents — the per-domain twin of
    resident_podlabels, for topo-scoped terms.  Domain ids are disjoint
    across topology keys (packer invariant), so one [D, K] table serves
    every key."""
    TK = snap.node_key_domain.shape[1]
    D = snap.domain_mask.shape[0]
    K = snap.task_podlabels.shape[1]
    held = _resident_mask(snap, state, include_releasing)
    w = held.astype(snap.task_podlabels.dtype)[:, None]
    node_of = jnp.clip(state.task_node, 0, snap.num_nodes - 1)
    onehot_lab = jax.nn.one_hot(
        snap.topo_term_label, K, dtype=snap.task_podlabels.dtype
    )  # [K2, K]
    Hd = jnp.zeros((D, K), snap.task_podlabels.dtype)
    Ad = jnp.zeros((D, K), snap.task_podlabels.dtype)
    for tk in range(TK):  # static, small (# distinct topology keys)
        seg = jnp.where(held, snap.node_key_domain[node_of, tk], D)
        Hd = Hd + jax.ops.segment_sum(
            snap.task_podlabels * w, seg, num_segments=D + 1
        )[:D]
        anti_this_key = snap.task_anti_topo * (
            snap.topo_term_key == tk
        ).astype(snap.task_anti_topo.dtype)[None, :]
        anti_lab = anti_this_key @ onehot_lab                   # [T, K]
        Ad = Ad + jax.ops.segment_sum(anti_lab * w, seg, num_segments=D + 1)[:D]
    return Hd > 0, Ad > 0


def _topo_feasibility(snap, Hb, Hd, Ad_now, Hd_now):
    """(aff_ok, anti_sym_ok): bool[T, N] for the topo-scoped terms.

    `Hb` is the node-level resident-label table (for the bootstrap
    existence test — a term 'exists' if ANY resident anywhere carries
    the label, regardless of domain); Hd/Hd_now/Ad_now are the domain
    tables (future-oriented for affinity, releasing-inclusive for the
    anti/symmetry side when immediate).
    """
    f = snap.task_aff_topo.dtype
    A = snap.node_key_domain[:, snap.topo_term_key]             # i32[N, K2]
    present = Hd[A, snap.topo_term_label[None, :]].astype(f)    # [N, K2]

    need = jnp.sum(snap.task_aff_topo, axis=1, keepdims=True)
    have = snap.task_aff_topo @ present.T                       # [T, N]
    exists = jnp.any(Hb, axis=0)[snap.topo_term_label]          # bool[K2]
    own_at_term = snap.task_podlabels[:, snap.topo_term_label]  # [T, K2]
    bootstrap = jnp.sum(
        snap.task_aff_topo * own_at_term * (~exists).astype(f)[None, :],
        axis=1, keepdims=True,
    )
    aff_ok = have + bootstrap >= need

    present_now = Hd_now[A, snap.topo_term_label[None, :]].astype(f)
    anti_hit = snap.task_anti_topo @ present_now.T              # [T, N]
    sym_hit = jnp.zeros_like(anti_hit)
    for tk in range(snap.node_key_domain.shape[1]):
        Ad_n = Ad_now[snap.node_key_domain[:, tk]].astype(f)    # [N, K]
        sym_hit = sym_hit + snap.task_podlabels @ Ad_n.T
    return aff_ok, (anti_hit <= 0.5) & (sym_hit <= 0.5)


def _affinity_tables(snap, state, immediate: bool):
    """Resident-side aggregates of the affinity predicate — node/domain
    label tables computed from the FULL task set (segment sums over the
    task axis, O(T·K), no [T, N] term).  Split out so the candidate
    side can run on a gathered subset (pod_affinity_subset)."""
    Hb, Ab = resident_podlabels(snap, state)
    if immediate:
        Hb_anti, Ab_anti = resident_podlabels(snap, state, include_releasing=True)
    else:
        Hb_anti, Ab_anti = Hb, Ab
    t = {"Hb": Hb, "Ab": Ab, "Hb_anti": Hb_anti, "Ab_anti": Ab_anti}
    if snap.task_aff_topo.shape[1]:  # static: topo terms exist
        Hd, Ad = resident_domain_labels(snap, state)
        if immediate:
            Hd_now, Ad_now = resident_domain_labels(
                snap, state, include_releasing=True
            )
        else:
            Hd_now, Ad_now = Hd, Ad
        t.update({"Hd": Hd, "Ad": Ad, "Hd_now": Hd_now, "Ad_now": Ad_now})
    return t


def _affinity_candidate_ok(cand, t):
    """bool[Tc, N] feasibility of `cand`'s task rows against the
    resident tables `t`.  `cand` may be the full snapshot or a
    gathered subset — only its task-axis arrays are read on the
    candidate side; node/vocab arrays are identical either way."""
    Hb = t["Hb"]
    Hf = Hb.astype(cand.task_aff.dtype)

    need = jnp.sum(cand.task_aff, axis=1, keepdims=True)       # f32[T,1]
    have = cand.task_aff @ Hf.T                                # f32[T,N]
    term_exists = jnp.any(Hb, axis=0)                          # bool[K]
    # Bootstrap waiver (k8s rule): a term NO pod in the cluster matches
    # is waived for ANY task that itself carries the label.  The auction
    # keeps this sound in a batched round by accepting at most ONE
    # bootstrap-dependent placement per round (see bootstrap_mask below
    # and ops/assignment.py's global-serialize step) — after it lands,
    # the term exists and the rest must genuinely co-locate.
    bootstrap = jnp.sum(
        cand.task_aff * (cand.task_podlabels > 0) * (~term_exists)[None, :],
        axis=1,
        keepdims=True,
    )                                                          # f32[T,1]
    aff_ok = have + bootstrap >= need

    anti_hit = cand.task_anti @ t["Hb_anti"].astype(Hf.dtype).T   # f32[T,N]
    sym_hit = cand.task_podlabels @ t["Ab_anti"].astype(Hf.dtype).T
    ok = aff_ok & (anti_hit <= 0.5) & (sym_hit <= 0.5)

    if cand.task_aff_topo.shape[1]:  # static: topo terms exist
        topo_aff_ok, topo_anti_ok = _topo_feasibility(
            cand, Hb, t["Hd"], t["Ad_now"], t["Hd_now"]
        )
        ok = ok & topo_aff_ok & topo_anti_ok
    return ok


def pod_affinity_predicate(snap, state, immediate: bool = False):
    """bool[T, N] inter-pod affinity/anti-affinity feasibility
    (≙ the vendored k8s inter-pod affinity predicate in
    plugins/predicates/predicates.go, topologyKey = node):

    * required affinity: every term names a label some resident of the
      node carries — with the k8s bootstrap rule (a term no pod in the
      whole cluster matches is waived when the task itself carries the
      label, so the first gang member can land);
    * anti-affinity: no resident carries any of the task's anti terms;
    * symmetry: no resident's anti term matches the task's own labels.

    `immediate` marks the Idle pass (placements that bind this cycle):
    the anti/symmetry checks then also see RELEASING residents, whose
    pods may outlive the bind on the cluster.  Positive affinity stays
    future-oriented in both passes — a dying pod is no anchor.
    """
    return _affinity_candidate_ok(snap, _affinity_tables(snap, state, immediate))


def pod_affinity_subset(snap, state, sub_snap, sub_state, immediate=False):
    """Active-set variant: candidate rows from the gathered `sub_snap`,
    residents from the FULL (snap, state) — exact, since residency is a
    property of placed tasks, which are never in the pending subset.
    (`sub_state` is unused: the candidate side is stateless.)"""
    del sub_state
    return _affinity_candidate_ok(
        sub_snap, _affinity_tables(snap, state, immediate)
    )


def pod_affinity_row(snap, state, p):
    """bool[N]: pod_affinity_predicate for ONE task — O(N·K) instead of
    the full [T, N] matrix; used per preemption step.  Future-oriented
    (the preemptor pipelines onto FutureIdle, after victims leave)."""
    Hb, Ab = resident_podlabels(snap, state)
    Hf = Hb.astype(snap.task_aff.dtype)
    aff = snap.task_aff[p]                                     # f32[K]
    own = snap.task_podlabels[p]
    term_exists = jnp.any(Hb, axis=0)
    need = jnp.sum(aff)
    have = Hf @ aff                                            # f32[N]
    bootstrap = jnp.sum(aff * (own > 0) * ~term_exists)
    aff_ok = have + bootstrap >= need
    anti_hit = Hf @ snap.task_anti[p]
    sym_hit = Ab.astype(Hf.dtype) @ own
    ok = aff_ok & (anti_hit <= 0.5) & (sym_hit <= 0.5)

    if snap.task_aff_topo.shape[1]:  # static: topo terms exist
        f = snap.task_aff_topo.dtype
        Hd, Ad = resident_domain_labels(snap, state)
        A = snap.node_key_domain[:, snap.topo_term_key]         # [N, K2]
        present = Hd[A, snap.topo_term_label[None, :]].astype(f)
        aff2 = snap.task_aff_topo[p]
        need2 = jnp.sum(aff2)
        have2 = present @ aff2                                  # f32[N]
        exists2 = term_exists[snap.topo_term_label]
        own2 = snap.task_podlabels[p, snap.topo_term_label]
        boot2 = jnp.sum(aff2 * own2 * (~exists2).astype(f))
        anti2 = present @ snap.task_anti_topo[p]
        sym2 = jnp.zeros(snap.num_nodes, f)
        for tk in range(snap.node_key_domain.shape[1]):
            Ad_n = Ad[snap.node_key_domain[:, tk]].astype(f)    # [N, K]
            sym2 = sym2 + Ad_n @ own
        ok = ok & (have2 + boot2 >= need2) & (anti2 <= 0.5) & (sym2 <= 0.5)
    return ok


def bootstrap_mask(snap, state):
    """bool[T]: tasks that may be accepted at most once per auction
    round GLOBALLY — pending tasks whose required affinity (node- or
    domain-scoped) currently relies on the bootstrap waiver: all of
    them placing at once would scatter a self-affine gang."""
    Hb, _ = resident_podlabels(snap, state)
    term_exists = jnp.any(Hb, axis=0)
    m = jnp.any(
        (snap.task_aff > 0) & (~term_exists)[None, :], axis=1
    )
    if snap.task_aff_topo.shape[1]:  # static: topo terms exist
        exists2 = term_exists[snap.topo_term_label]
        m = m | jnp.any(
            (snap.task_aff_topo > 0) & (~exists2)[None, :], axis=1
        )
    return m & snap.task_mask


def topo_anti_participants(snap, state):  # noqa: ARG001 — snapshot-static
    """bool[T]: tasks involved in DOMAIN-scoped anti-affinity (as term
    holder or as label target) — limited to one acceptance per topology
    domain per round (ops/assignment.py's domain-serialize step): two
    same-round acceptances on different nodes of one zone can't see
    each other in the residents table."""
    if not snap.task_anti_topo.shape[1]:  # static: no topo terms
        return jnp.zeros(snap.num_tasks, bool)
    used2 = jnp.any(snap.task_anti_topo > 0, axis=0)            # bool[K2]
    K = snap.task_podlabels.shape[1]
    anti_union2 = jnp.zeros(K, bool).at[snap.topo_term_label].max(used2)
    return (
        jnp.any(snap.task_anti_topo > 0, axis=1)
        | jnp.any((snap.task_podlabels > 0) & anti_union2[None, :], axis=1)
    ) & snap.task_mask
