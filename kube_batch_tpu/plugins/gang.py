"""Gang plugin: all-or-nothing minMember scheduling.

Reference counterpart: plugins/gang/gang.go —
* JobValidFn: a job may only be considered if enough tasks could still
  become ready (ValidTaskNum ≥ MinAvailable);
* JobReadyFn: binds dispatch only once ReadyTaskNum ≥ MinAvailable;
* JobOrderFn: jobs still fighting for their gang come first;
* PreemptableFn: vetoes victims whose job would drop below MinAvailable;
* OnSessionClose: surfaces "job cannot reach minMember" to users via
  events + PodGroup conditions.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from kube_batch_tpu.api.snapshot import job_ready_counts, job_valid_counts
from kube_batch_tpu.framework.plugin import Plugin, register_plugin


@register_plugin
class GangPlugin(Plugin):
    name = "gang"

    def register(self, policy, tier: int) -> None:
        def job_valid(snap, state):
            return job_valid_counts(snap, state.task_state) >= snap.job_min

        def job_ready(snap, state):
            return job_ready_counts(snap, state.task_state) >= snap.job_min

        def job_pipelined(snap, state):
            # ready+pipelined members suffice → job may wait on releasing
            # resources instead of being preempted-for.
            from kube_batch_tpu.api.snapshot import count_per_job, status_is
            from kube_batch_tpu.api.types import READY_STATUSES, TaskStatus

            cnt = count_per_job(
                snap,
                status_is(state.task_state, *READY_STATUSES, TaskStatus.PIPELINED),
            )
            return cnt >= snap.job_min

        def job_order(snap, state):
            # unready gangs first (key 0.0), satisfied gangs later (1.0)
            return job_ready(snap, state).astype(jnp.float32)

        def preemptable(snap, state, preemptor):  # noqa: ARG001
            # veto evicting a task if its job would fall below minMember
            ready = job_ready_counts(snap, state.task_state)
            tj = jnp.clip(snap.task_job, 0, snap.num_jobs - 1)
            survives = ready[tj] - 1 >= snap.job_min[tj]
            return survives | (snap.task_job < 0)

        if self.enabled_for("jobValid"):
            policy.add_job_valid_fn(job_valid)
        if self.enabled_for("jobReady"):
            policy.add_job_ready_fn(job_ready)
            policy.add_job_pipelined_fn(job_pipelined)
        if self.enabled_for("jobOrder"):
            policy.add_job_order_fn(tier, job_order)
        if self.enabled_for("preemptable"):
            policy.add_preemptable_fn(tier, preemptable)
        if self.enabled_for("reclaimable"):
            policy.add_reclaimable_fn(tier, preemptable)

    def on_session_close(self, ssn) -> None:
        """Emit unschedulable events + typed PodGroup conditions for
        unready gangs (≙ gang.go · OnSessionClose), through the cache's
        recorder/condition funnels — never private cache state."""
        from kube_batch_tpu.api.types import PodGroupCondition

        # Counts come from the frozen packed snapshot, not live Pod
        # statuses — the shared snapshot's pods keep mutating after the
        # cycle's lock is released (session.snapshot_ready_counts).
        ready_counts = ssn.snapshot_ready_counts()
        job_min = ssn.host_snap_field("job_min")
        name_to_idx = {n: i for i, n in enumerate(ssn.meta.job_names)}
        for name in ssn.unready_jobs():
            j = name_to_idx.get(name)
            if j is None:
                continue
            msg = (
                f"gang unschedulable: job {name} has {int(ready_counts[j])} "
                f"ready, needs minMember {int(job_min[j])}"
            )
            ssn.cache.record_event("PodGroup", name, "Unschedulable", msg)
            ssn.cache.add_job_condition(
                name,
                PodGroupCondition(
                    type="Unschedulable", reason="NotEnoughResources",
                    message=msg,
                ),
            )
