"""Nodeorder plugin: node scoring for placement quality.

Reference counterpart: plugins/nodeorder/nodeorder.go — NodeOrderFn as a
weighted sum of the upstream k8s priorities (LeastRequestedPriority,
BalancedResourceAllocation, NodeAffinityPriority), weights configurable
via Arguments.

Each priority is a pure f32[T, N] tensor term over the snapshot plus the
LIVE AllocState (node_idle shrinks as auction rounds land placements, so
spreading/balancing reacts within a cycle — strictly fresher than the
reference, which scores against the session snapshot):

* least-requested:  mean_r (idle_after_this_task / capacity) · 10
  — prefer emptier nodes, the classic spreading score;
* balanced-allocation:  10 − |cpu_frac − mem_frac| · 10 with
  frac = (used + req) / capacity — avoid lopsided nodes;
* node-affinity:  Σ weights of preferred labels the node carries
  (task_pref @ node_labelsᵀ), normalized to 0–10 per the upstream
  CalculateNodeAffinityPriority normalization.

Arguments (≙ nodeorder.go's Arguments):
    nodeorder.leastrequested.weight     (default 1)
    nodeorder.balancedresource.weight   (default 1)
    nodeorder.nodeaffinity.weight       (default 1)
"""

from __future__ import annotations

import jax.numpy as jnp

from kube_batch_tpu.framework.plugin import Plugin, register_plugin

MAX_SCORE = 10.0


@register_plugin
class NodeOrderPlugin(Plugin):
    name = "nodeorder"

    def register(self, policy, tier: int) -> None:  # noqa: ARG002
        if not self.enabled_for("nodeOrder"):
            return
        w_least = self.args.get_float("nodeorder.leastrequested.weight", 1.0)
        w_bal = self.args.get_float("nodeorder.balancedresource.weight", 1.0)
        w_aff = self.args.get_float("nodeorder.nodeaffinity.weight", 1.0)

        # Both dynamic scores read state.node_future, not node_idle:
        # node_future shrinks with placements in BOTH allocate passes
        # (idle and pipelining — see ops/assignment.py · allocate_rounds),
        # so spreading keeps reacting while pipelined placements land,
        # where node_idle would be frozen for the whole future pass.
        def least_requested(snap, state):
            cap = jnp.maximum(snap.node_cap, 1e-9)              # f32[N,R]
            idle_after = state.node_future[None, :, :] - snap.task_req[:, None, :]
            frac = jnp.clip(idle_after, 0.0, None) / cap[None, :, :]
            # Average only over dims the TASK requests (upstream averages
            # cpu+memory only): dims a pod doesn't ask for must not steer
            # it — a plain pod averaging an accelerator dim would either
            # flock to or flee accelerator nodes depending on their
            # occupancy, blocking later accelerator jobs either way.
            w = (snap.task_req > 0.0).astype(jnp.float32)[:, None, :]
            num = jnp.sum(frac * w, axis=-1)
            return num / jnp.maximum(jnp.sum(w, axis=-1), 1.0) * MAX_SCORE

        # upstream balances cpu vs memory; the spec convention (see
        # api/resource.py · ResourceSpec) puts them at dims 0 and 1,
        # overridable for exotic specs via Arguments.
        d0 = self.args.get_int("nodeorder.balancedresource.dim0", 0)
        d1 = self.args.get_int("nodeorder.balancedresource.dim1", 1)

        def balanced(snap, state):
            if snap.num_resources < 2:
                return jnp.zeros((snap.num_tasks, snap.num_nodes), jnp.float32)
            cap = jnp.maximum(snap.node_cap, 1e-9)
            used_after = (
                (snap.node_cap - state.node_future)[None, :, :]
                + snap.task_req[:, None, :]
            )
            frac = jnp.clip(used_after / cap[None, :, :], 0.0, 1.0)
            diff = jnp.abs(frac[..., d0] - frac[..., d1])
            return (1.0 - diff) * MAX_SCORE                     # f32[T,N]

        def node_affinity(snap, state):  # noqa: ARG001
            raw = snap.task_pref @ snap.node_labels.T           # f32[T,N]
            denom = jnp.maximum(jnp.sum(snap.task_pref, axis=1), 1e-9)
            return raw / denom[:, None] * MAX_SCORE

        w_podaff = self.args.get_float("nodeorder.podaffinity.weight", 1.0)

        def pod_affinity_score(snap, state):
            """Preferred co-location (≙ InterPodAffinityPriority):
            weighted sum of soft terms matched by the node's residents —
            node-level terms against the node's own residents, topology-
            scoped terms ("zone:app=web") against the residents of the
            node's DOMAIN under that key — normalized to MAX_SCORE over
            the task's total preference weight."""
            from kube_batch_tpu.plugins.predicates import (
                resident_domain_labels,
                resident_podlabels,
            )

            Hb, _ = resident_podlabels(snap, state)
            raw = snap.task_podpref @ Hb.astype(jnp.float32).T  # f32[T,N]
            total_w = jnp.sum(snap.task_podpref, axis=1)
            if snap.task_podpref_topo.shape[1]:  # static: topo terms exist
                Hd, _ = resident_domain_labels(snap, state)
                A = snap.node_key_domain[:, snap.topo_term_key]  # i32[N,K2]
                present = Hd[A, snap.topo_term_label[None, :]]   # bool[N,K2]
                raw = raw + snap.task_podpref_topo @ present.astype(
                    jnp.float32
                ).T
                total_w = total_w + jnp.sum(snap.task_podpref_topo, axis=1)
            denom = jnp.maximum(total_w, 1e-9)
            return raw / denom[:, None] * MAX_SCORE

        if w_least:
            policy.add_node_order_fn(w_least, least_requested)
        if w_bal:
            policy.add_node_order_fn(w_bal, balanced)
        if w_aff:
            policy.add_node_order_fn(w_aff, node_affinity, state_dependent=False)
        if w_podaff:
            policy.add_node_order_fn(w_podaff, pod_affinity_score)
        quantum = self.args.get_float("nodeorder.quantum", 0.0)
        if quantum > 0.0:
            policy.score_quantum = quantum
