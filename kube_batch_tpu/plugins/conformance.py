"""Conformance plugin: never evict cluster-critical pods.

Reference counterpart: plugins/conformance/conformance.go — a
PreemptableFn/ReclaimableFn that filters candidate victims, excluding
pods in kube-system and pods whose priority class is
system-cluster-critical / system-node-critical.

The critical bit is resolved at pack time (cache/cluster.py ·
Pod.critical → snapshot task_critical), so the veto is a single mask.
"""

from __future__ import annotations

from kube_batch_tpu.framework.plugin import Plugin, register_plugin


@register_plugin
class ConformancePlugin(Plugin):
    name = "conformance"

    def register(self, policy, tier: int) -> None:
        def not_critical(snap, state, preemptor):  # noqa: ARG001
            return ~snap.task_critical

        if self.enabled_for("preemptable"):
            policy.add_preemptable_fn(tier, not_critical)
        if self.enabled_for("reclaimable"):
            policy.add_reclaimable_fn(tier, not_critical)
