"""DRF plugin: Dominant Resource Fairness job ordering + preemption.

Reference counterpart: plugins/drf/drf.go —
* per-job share = max over resources of allocated_r / clusterTotal_r;
* JobOrderFn: lower dominant share scheduled first;
* PreemptableFn: a victim is allowed only if its job's share after the
  eviction stays ≥ the preemptor job's share — preemption may narrow
  the dominance gap but never invert it.

The reference maintains shares incrementally via Allocate/Deallocate
EventHandlers; here shares are pure reductions over the live AllocState,
recomputed wherever consulted (each auction round, each veto sweep), so
the in-cycle feedback loop the reference gets from handlers falls out
of referential transparency.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import (
    SnapshotTensors,
    allocated_mask,
    status_is,
    sum_req_per_job,
)
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.framework.plugin import Plugin, register_plugin
from kube_batch_tpu.ops.assignment import AllocState


def job_allocated(snap: SnapshotTensors, state: AllocState) -> jax.Array:
    """f32[J, R]: resources currently held by each job's tasks
    (pipelined placements count — the reference fires the same allocate
    EventHandlers for ssn.Pipeline)."""
    held = allocated_mask(state.task_state) | status_is(
        state.task_state, TaskStatus.PIPELINED
    )
    return sum_req_per_job(snap, held)


def share_of(alloc: jax.Array, total: jax.Array) -> jax.Array:
    """Dominant share: max over resource dims of alloc/total."""
    return jnp.max(alloc / jnp.maximum(total, 1e-9), axis=-1)


def job_share(snap: SnapshotTensors, state: AllocState) -> jax.Array:
    """f32[J]: dominant share (drf.go · calculateShare)."""
    return share_of(job_allocated(snap, state), snap.cluster_total)


def ns_allocated(snap: SnapshotTensors, state: AllocState) -> jax.Array:
    """f32[S, R]: resources currently held per namespace."""
    held = (
        allocated_mask(state.task_state)
        | status_is(state.task_state, TaskStatus.PIPELINED)
    ) & snap.task_mask & (snap.task_ns >= 0)
    S = snap.ns_weight.shape[0]
    seg = jnp.where(held, jnp.clip(snap.task_ns, 0, S - 1), S)
    return jax.ops.segment_sum(
        jnp.where(held[:, None], snap.task_req, 0.0),
        seg, num_segments=S + 1,
    )[:S]


def ns_share(snap: SnapshotTensors, state: AllocState) -> jax.Array:
    """f32[S]: weighted dominant share per namespace — allocated /
    (clusterTotal · weight), lower served first (≙ the reference's
    NamespaceOrderFn over api/namespace_info.go weights)."""
    w = jnp.maximum(snap.ns_weight, 1e-9)[:, None]
    return share_of(
        ns_allocated(snap, state) / w, snap.cluster_total
    )


@register_plugin
class DrfPlugin(Plugin):
    name = "drf"

    def register(self, policy, tier: int) -> None:
        def job_order(snap, state):
            return job_share(snap, state)

        def preemptable(snap, state, preemptor):
            alloc = job_allocated(snap, state)                    # f32[J, R]
            total = snap.cluster_total
            pj = jnp.clip(snap.task_job[preemptor], 0, snap.num_jobs - 1)
            preemptor_share = share_of(alloc[pj], total)          # f32[]
            tj = jnp.clip(snap.task_job, 0, snap.num_jobs - 1)
            victim_after = alloc[tj] - snap.task_req              # f32[T, R]
            victim_share_after = share_of(victim_after, total)    # f32[T]
            return (victim_share_after >= preemptor_share) | (snap.task_job < 0)

        def job_vtime(snap, state, base_rank, valid):
            """Per-task virtual start times in dominant-share space —
            the WFQ embedding of drf.go's per-placement share feedback."""
            from kube_batch_tpu.framework.policy import virtual_start_times

            total = jnp.broadcast_to(
                jnp.maximum(snap.cluster_total, 1e-9)[None, :],
                (snap.num_jobs, snap.num_resources),
            )
            return virtual_start_times(
                snap.task_job,
                base_rank,
                snap.task_req,
                valid,
                job_allocated(snap, state),
                total,
                snap.num_jobs,
            )

        def namespace_order(snap, state):
            return ns_share(snap, state)

        def ns_vtime(snap, state, base_rank, valid):
            """WFQ virtual start times in weighted namespace-share
            space — serves namespaces within a queue by weighted
            fairness at per-task granularity."""
            from kube_batch_tpu.framework.policy import virtual_start_times

            S = snap.ns_weight.shape[0]
            denom = jnp.maximum(snap.cluster_total, 1e-9)[None, :] * (
                jnp.maximum(snap.ns_weight, 1e-9)[:, None]
            )
            return virtual_start_times(
                snap.task_ns,
                base_rank,
                snap.task_req,
                valid,
                ns_allocated(snap, state),
                denom,
                S,
            )

        if self.enabled_for("jobOrder"):
            policy.add_job_order_fn(tier, job_order)
            policy.add_job_vtime_fn(tier, job_vtime)
        if self.enabled_for("namespaceOrder"):
            policy.add_namespace_order_fn(tier, namespace_order)
            policy.add_namespace_vtime_fn(tier, ns_vtime)
        if self.enabled_for("preemptable"):
            policy.add_preemptable_fn(tier, preemptable)
