"""Proportion plugin: weighted fair queue shares.

Reference counterpart: plugins/proportion/proportion.go —
* per-queue `deserved` via weighted water-filling of the cluster total,
  clamped by the queue's own request (ops/waterfill.py);
* QueueOrderFn: share = allocated/deserved, lower share served first;
* OverusedFn: a queue at or above its deserved gets no more allocations;
* ReclaimableFn: a queue only gives up victims while it stays at or
  above deserved after the eviction (reclaim only taxes surplus).

The reference keeps these up to date with EventHandlers firing after
every allocation; here every fn recomputes from the live `AllocState`,
so in-round feedback is automatic.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import (
    SnapshotTensors,
    allocated_mask,
    status_is,
)
from kube_batch_tpu.api.types import TaskStatus
from kube_batch_tpu.framework.plugin import Plugin, register_plugin
from kube_batch_tpu.framework.policy import task_queue_of
from kube_batch_tpu.ops.assignment import AllocState
from kube_batch_tpu.ops.waterfill import waterfill_deserved

BIG_SHARE = 1e9


def queue_allocated(snap: SnapshotTensors, state: AllocState) -> jax.Array:
    """f32[Q, R]: requests currently held per queue (live, in-cycle).

    Pipelined placements count: the reference fires the same allocate
    EventHandlers for ssn.Pipeline, so shares move for them too.
    """
    tq = task_queue_of(snap)
    held = (
        allocated_mask(state.task_state)
        | status_is(state.task_state, TaskStatus.PIPELINED)
    ) & snap.task_mask & (snap.task_job >= 0)
    seg = jnp.where(held, tq, snap.num_queues)
    return jax.ops.segment_sum(
        jnp.where(held[:, None], snap.task_req, 0.0),
        seg,
        num_segments=snap.num_queues + 1,
    )[: snap.num_queues]


def queue_request(snap: SnapshotTensors) -> jax.Array:
    """f32[Q, R]: total request of every task in the queue's jobs
    (≙ proportion.go summing JobInfo.TotalRequest per queue)."""
    tq = task_queue_of(snap)
    valid = snap.task_mask & (snap.task_job >= 0)
    seg = jnp.where(valid, tq, snap.num_queues)
    return jax.ops.segment_sum(
        jnp.where(valid[:, None], snap.task_req, 0.0),
        seg,
        num_segments=snap.num_queues + 1,
    )[: snap.num_queues]


DESERVED_AUX = "proportion/deserved"


def queue_deserved(snap: SnapshotTensors) -> jax.Array:
    """f32[Q, R] water-filled deserved (state-independent within a cycle)."""
    return waterfill_deserved(
        snap.queue_weight, queue_request(snap), snap.cluster_total, snap.queue_mask
    )


def _deserved(snap: SnapshotTensors, state: AllocState) -> jax.Array:
    """Per-cycle cached deserved when the solver ran setup_state; fresh
    computation otherwise (host-side callers like dispatch gating)."""
    cached = state.aux.get(DESERVED_AUX)
    return cached if cached is not None else queue_deserved(snap)


def victim_stays_above_deserved(
    snap: SnapshotTensors, state: AllocState
) -> jax.Array:
    """bool[T]: evicting this task leaves its queue at or above its
    water-filled deserved share (per meaningful dimension — counting
    dims like pod slots are excluded via besteffort_eps: upstream's
    Resource has no pod-count dimension; node pod capacity is
    MaxTaskNum in predicates, never part of proportion math).

    Single source of truth for the deserved floor — used both by the
    registered ReclaimableFn below and by the reclaim action's inline
    victim gate (≙ reclaim.go's own allocations-vs-deserved check).
    """
    alloc = queue_allocated(snap, state)
    deserved = _deserved(snap, state)
    tq = task_queue_of(snap)
    after = alloc[tq] - snap.task_req
    return jnp.all(
        (deserved[tq] <= after) | (deserved[tq] < snap.besteffort_eps[None, :]),
        axis=1,
    )


def queue_share(snap: SnapshotTensors, state: AllocState) -> jax.Array:
    """f32[Q]: max-dimension allocated/deserved ratio (lower = hungrier)."""
    alloc = queue_allocated(snap, state)
    deserved = _deserved(snap, state)
    ratio = jnp.where(
        deserved > 0.0, alloc / jnp.maximum(deserved, 1e-9),
        jnp.where(alloc > 0.0, BIG_SHARE, 0.0),
    )
    return jnp.max(ratio, axis=1)


@register_plugin
class ProportionPlugin(Plugin):
    name = "proportion"

    def register(self, policy, tier: int) -> None:
        def queue_order(snap, state):
            return queue_share(snap, state)

        def overused(snap, state):
            # deserved ⊑ allocated (all meaningful dims; counting dims
            # excluded via besteffort_eps) → no more for you
            alloc = queue_allocated(snap, state)
            deserved = _deserved(snap, state)
            return jnp.all(
                (deserved <= alloc) | (deserved < snap.besteffort_eps[None, :]),
                axis=1,
            ) & snap.queue_mask

        def reclaimable(snap, state, preemptor):  # noqa: ARG001
            # victim allowed only if its queue stays ≥ deserved afterwards
            return victim_stays_above_deserved(snap, state) | (
                snap.task_job < 0
            )

        def queue_vtime(snap, state, base_rank, valid):
            """Per-task virtual start times in allocated/deserved share
            space — the WFQ embedding of the reference's queue-share
            feedback (see framework/policy.py · virtual_start_times)."""
            from kube_batch_tpu.framework.policy import virtual_start_times

            return virtual_start_times(
                task_queue_of(snap),
                base_rank,
                snap.task_req,
                valid,
                queue_allocated(snap, state),
                _deserved(snap, state),
                snap.num_queues,
            )

        policy.add_cycle_setup_fn(DESERVED_AUX, queue_deserved)
        if self.enabled_for("queueOrder"):
            policy.add_queue_order_fn(tier, queue_order)
            policy.add_queue_vtime_fn(tier, queue_vtime)
        if self.enabled_for("overused"):
            policy.add_overused_fn(overused)
        if self.enabled_for("reclaimable"):
            policy.add_reclaimable_fn(tier, reclaimable)
