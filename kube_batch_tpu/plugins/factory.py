"""Plugin factory: importing it registers every built-in plugin
(≙ plugins/factory.go)."""

from kube_batch_tpu.plugins import gang, priority  # noqa: F401

BUILTIN_PLUGINS = ["gang", "priority"]
