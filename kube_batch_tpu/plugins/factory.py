"""Plugin factory: importing it registers every built-in plugin
(≙ plugins/factory.go)."""

from kube_batch_tpu.plugins import drf, gang, priority, proportion  # noqa: F401

BUILTIN_PLUGINS = ["drf", "gang", "priority", "proportion"]
