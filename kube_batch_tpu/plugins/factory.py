"""Plugin factory: importing it registers every built-in plugin
(≙ plugins/factory.go)."""

from kube_batch_tpu.plugins import (  # noqa: F401
    conformance,
    drf,
    gang,
    nodeorder,
    pdb,
    predicates,
    priority,
    proportion,
)

BUILTIN_PLUGINS = [
    "conformance",
    "drf",
    "gang",
    "nodeorder",
    "pdb",
    "predicates",
    "priority",
    "proportion",
]
