"""Policy plugins (≙ pkg/scheduler/plugins).

Importing this package registers every built-in plugin with the
framework registry (≙ plugins/factory.go).
"""

from kube_batch_tpu.plugins import factory  # noqa: F401  (registration side effect)
from kube_batch_tpu.plugins.factory import BUILTIN_PLUGINS

__all__ = ["BUILTIN_PLUGINS"]
