"""PDB plugin: PodDisruptionBudget-aware eviction vetoes.

Reference counterpart: the PDB the reference carries on each job
(api/job_info.go · JobInfo.PDB) and honors when filtering preemption/
reclaim victims — plain pods matching a budget's selector may not be
evicted below its minAvailable.  The gang plugin provides the analogous
floor for gang members; this plugin covers everything else.

Tensor shape: the packer resolves each pod's matching budgets into the
multi-hot `task_pdbs` (f32[T, B] — ALL budgets whose selector matches,
not just the first) and the floors into `pdb_min` (i32[B]); the veto is
then one matmul per sweep step, recomputed against the LIVE state so
cumulative evictions within one Statement keep respecting every floor.
A pod under several budgets is evictable only if ALL of them survive
the eviction (intersection semantics).

Known divergence: Kubernetes' eviction API refuses eviction OUTRIGHT
for a pod covered by more than one budget (apiserver returns 500,
regardless of headroom); this plugin instead allows it when every
covering budget keeps its floor.  Intersection is strictly safer than
first-match and never violates any individual budget, but it is more
permissive than upstream's hard multi-PDB refusal.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import allocated_mask
from kube_batch_tpu.framework.plugin import Plugin, register_plugin


def pdb_healthy_counts(snap, state) -> jax.Array:
    """i32[B]: currently-healthy (resource-holding) members per budget.
    A pod belonging to several budgets counts toward each of them."""
    member = (
        allocated_mask(state.task_state) & snap.task_mask
    ).astype(snap.task_pdbs.dtype)
    return (member @ snap.task_pdbs).astype(jnp.int32)  # f32[T] @ f32[T,B]


@register_plugin
class PdbPlugin(Plugin):
    name = "pdb"

    def register(self, policy, tier: int) -> None:
        def veto(snap, state, preemptor):  # noqa: ARG001 — budget is global
            B = snap.pdb_min.shape[0]
            if B == 0:  # static: no budgets in this snapshot
                return jnp.ones(snap.num_tasks, bool)
            healthy = pdb_healthy_counts(snap, state)
            # A budget is "at the floor" when losing one more member
            # would violate it; a task survives the veto only if NONE of
            # its budgets are at the floor (intersection over budgets).
            at_floor = (healthy - 1 < snap.pdb_min).astype(snap.task_pdbs.dtype)
            violated = snap.task_pdbs @ at_floor  # f32[T]
            return violated <= 0.5

        if self.enabled_for("preemptable"):
            policy.add_preemptable_fn(tier, veto)
        if self.enabled_for("reclaimable"):
            policy.add_reclaimable_fn(tier, veto)
