"""PDB plugin: PodDisruptionBudget-aware eviction vetoes.

Reference counterpart: the PDB the reference carries on each job
(api/job_info.go · JobInfo.PDB) and honors when filtering preemption/
reclaim victims — plain pods matching a budget's selector may not be
evicted below its minAvailable.  The gang plugin provides the analogous
floor for gang members; this plugin covers everything else.

Tensor shape: the packer resolves each pod's (first) matching budget
into `task_pdb` (i32[T]) and the floors into `pdb_min` (i32[B]); the
veto is then one segment count + gather per sweep step, recomputed
against the LIVE state so cumulative evictions within one Statement
keep respecting the floor.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kube_batch_tpu.api.snapshot import allocated_mask
from kube_batch_tpu.framework.plugin import Plugin, register_plugin


def pdb_healthy_counts(snap, state) -> jax.Array:
    """i32[B]: currently-healthy (resource-holding) members per budget."""
    B = snap.pdb_min.shape[0]
    member = (
        allocated_mask(state.task_state)
        & snap.task_mask
        & (snap.task_pdb >= 0)
    )
    seg = jnp.where(member, jnp.clip(snap.task_pdb, 0, B - 1), B)
    return jax.ops.segment_sum(
        jnp.ones_like(seg, dtype=jnp.int32), seg, num_segments=B + 1
    )[:B]


@register_plugin
class PdbPlugin(Plugin):
    name = "pdb"

    def register(self, policy, tier: int) -> None:
        def veto(snap, state, preemptor):  # noqa: ARG001 — budget is global
            B = snap.pdb_min.shape[0]
            if B == 0:  # static: no budgets in this snapshot
                return jnp.ones(snap.num_tasks, bool)
            healthy = pdb_healthy_counts(snap, state)
            tb = jnp.clip(snap.task_pdb, 0, B - 1)
            survives = healthy[tb] - 1 >= snap.pdb_min[tb]
            return survives | (snap.task_pdb < 0)

        if self.enabled_for("preemptable"):
            policy.add_preemptable_fn(tier, veto)
        if self.enabled_for("reclaimable"):
            policy.add_reclaimable_fn(tier, veto)
