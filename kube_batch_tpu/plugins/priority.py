"""Priority plugin: order tasks/jobs by priority value.

Reference counterpart: plugins/priority/priority.go — TaskOrderFn by pod
spec.priority, JobOrderFn by PodGroup priority-class value.  Keys are
negated priorities (framework order keys sort ascending).
"""

from __future__ import annotations

from kube_batch_tpu.framework.plugin import Plugin, register_plugin


@register_plugin
class PriorityPlugin(Plugin):
    name = "priority"

    def register(self, policy, tier: int) -> None:
        if self.enabled_for("taskOrder"):
            policy.add_task_order_fn(tier, lambda snap, state: -snap.task_prio)
        if self.enabled_for("jobOrder"):
            policy.add_job_order_fn(tier, lambda snap, state: -snap.job_prio)
