"""`python -m kube_batch_tpu` → the CLI (≙ cmd/kube-batch/main.go)."""

import sys

from kube_batch_tpu.cli import main

sys.exit(main())
